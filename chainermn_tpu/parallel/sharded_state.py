"""Unified sharded-state layer: per-leaf layout signatures driving
ZeRO-2/3 state, plan-IR-tuned exchanges, and JIT per-layer gathers.

PR 19 made every exchange a searchable plan-IR program; the *state*
side stayed fragmented — ZeRO-1 in ``training/optimizers.py``, FSDP in
``parallel/fsdp.py``, elastic re-layout speaking only the ZeRO-1 layout
(``_zero1_leaf_layout``).  This module is the one signature in the
spirit of "Automatic Cross-Replica Sharding" (PAPERS.md 2004.13336)
that also drives the 2112.01075-style redistribution already in
``relayout_state``:

- :class:`LeafLayout` — one leaf's layout: tree path, kind, full
  shape/dtype, world, shard dim.  ``to_record()`` emits exactly the
  JSON records ``topology_signature`` stamps into snapshots (the
  ZeRO-1 ``shard``/``stack``/``rep`` vocabulary, extended with
  ``fsdp`` for dim-sharded ZeRO-3 leaves), so every consumer —
  elastic re-layout, shard-only save sets, the plan IR's payload
  descriptors, the memory accountant — reads the SAME source of truth.
- :func:`state_layout_table` — the per-mode builder: ``zero1``/
  ``zero2`` state is world-stacked flat shards (the
  ``zero1_optimizer`` ``_leaf_shard`` layout, identified by the same
  longest-path-suffix match ``shard_opt_state`` uses); ``zero3``
  params and mirrored optimizer moments are dim-sharded per
  ``fsdp_dims``.
- :func:`gather_state_leaves` / :func:`shard_state_leaves` — the
  host-side gather/scatter over ANY layout table (the unified layer
  behind the deprecated ``gather_zero1_leaves``/``shard_zero1_leaves``
  shims in ``training/elastic.py``).
- :class:`ShardedState` — the ZeRO-3/FSDP plan: params (and their
  elementwise optimizer state) live 1/world at rest, are gathered
  just-in-time per layer through :class:`LayerGatherStream`, and the
  gather program is tuned/cached via ``autotune_pattern_plan
  (pattern="fsdp_gather")`` with the payload descriptors derived from
  this table (``ops.plan_ir.describe_state_payload``).
- :class:`LayerGatherStream` — the JIT layer gather with a PREFETCH
  WINDOW: gathering layer ``i + window`` is gated (via
  ``lax.optimization_barrier`` token threading — the barrier
  transposes to itself, so AD's reduce-scatter is untouched) on layer
  ``i``'s compute having retired, so at most ``window`` layers of
  full-width params are live at once while the next layer's gather
  overlaps the current layer's compute.
  ``utils.comm_model.choose_gather_prefetch_depth`` sizes the window
  from the latency/bandwidth model.

ZeRO-2 itself lives with its siblings in ``training/optimizers.py``
(:func:`~chainermn_tpu.training.optimizers.zero2_optimizer` — the
per-bucket reduce-scatter IS the gradient exchange); its state layout
is the ZeRO-1 table here, which is why elastic resize and shard-only
snapshots handle it with zero new code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "LAYOUT_KINDS",
    "LeafLayout",
    "LayerGatherStream",
    "ShardedState",
    "gather_state_leaves",
    "layout_records",
    "shard_state_leaves",
    "state_layout_table",
    "zero_opt_layouts",
]

#: the layout vocabulary — ``shard``/``stack``/``rep`` are the ZeRO-1
#: records every existing snapshot already carries; ``fsdp`` is the
#: dim-sharded ZeRO-3 extension.
LAYOUT_KINDS = ("rep", "stack", "shard", "fsdp")

SHARDING_MODES = ("zero1", "zero2", "zero3")


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# --------------------------------------------------------------------- #
# the layout signature
# --------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class LeafLayout:
    """One leaf's layout signature: how this param/grad/optimizer leaf
    is laid out across ``world`` members.

    ``shape``/``dtype`` describe the FULL (gathered) leaf; the at-rest
    per-member view follows from ``kind``:

    - ``rep`` — replicated, every member holds the full leaf;
    - ``stack`` — a leading member axis over per-member replicas
      (adam's ``count`` under the world-stacked carry);
    - ``shard`` — a ``(world, ceil(size/world))`` stack of flat ZeRO-1/2
      shards (``size`` = the mirrored parameter's true element count;
      padding lanes zero);
    - ``fsdp`` — dim-sharded ZeRO-3: dim ``dim`` split evenly over the
      world (``shape[dim] % world == 0`` by ``fsdp_dims`` construction).

    ``axis`` names the mesh axis the sharding lives on (``None`` for
    ``rep``).  ``to_record()``/``from_record()`` round-trip the
    JSON-stable form ``topology_signature`` stamps — bit-compatible
    with the records ``_zero1_leaf_layout`` has always written.
    """

    path: Tuple[str, ...]
    kind: str
    shape: Tuple[int, ...]
    dtype: str
    world: int
    dim: Optional[int] = None       # fsdp shard dim
    size: Optional[int] = None      # shard true element count
    axis: Optional[str] = None

    def __post_init__(self):
        if self.kind not in LAYOUT_KINDS:
            raise ValueError(
                f"unknown layout kind {self.kind!r}; expected one of "
                f"{LAYOUT_KINDS}")
        if self.kind == "shard" and self.size is None:
            raise ValueError(f"{'/'.join(self.path)}: shard layout "
                             "needs the true element count (size=)")
        if self.kind == "fsdp" and self.dim is None:
            raise ValueError(f"{'/'.join(self.path)}: fsdp layout "
                             "needs the shard dim (dim=)")

    # -- geometry ------------------------------------------------------ #

    @property
    def global_size(self) -> int:
        n = 1
        for s in self.shape:
            n *= int(s)
        return n

    def local_shape(self, world: Optional[int] = None) -> Tuple[int, ...]:
        """The at-rest PER-MEMBER shape (one member's slice)."""
        w = int(world if world is not None else self.world)
        if self.kind == "shard":
            return (_ceil_div(int(self.size), w),)
        if self.kind == "fsdp":
            shape = list(self.shape)
            d = int(self.dim)
            if shape[d] % w:
                raise ValueError(
                    f"{'/'.join(self.path)}: fsdp dim {d} (length "
                    f"{shape[d]}) not divisible by world {w}")
            shape[d] //= w
            return tuple(shape)
        # rep and stack both hold the full leaf per member (a stack's
        # member rows are replicas)
        return tuple(self.shape)

    def local_bytes(self, world: Optional[int] = None) -> int:
        n = 1
        for s in self.local_shape(world):
            n *= int(s)
        return n * np.dtype(self.dtype).itemsize

    def global_bytes(self) -> int:
        return self.global_size * np.dtype(self.dtype).itemsize

    # -- the JSON record ------------------------------------------------ #

    def to_record(self) -> dict:
        """The snapshot-stamped record — EXACTLY the
        ``_zero1_leaf_layout`` vocabulary for the legacy kinds, so
        every existing topology signature stays readable."""
        if self.kind == "shard":
            return {"kind": "shard", "size": int(self.size)}
        if self.kind == "fsdp":
            return {"kind": "fsdp", "dim": int(self.dim),
                    "len": int(self.shape[self.dim])}
        return {"kind": self.kind}

    @classmethod
    def from_record(cls, record: dict, *, path: Tuple[str, ...] = (),
                    shape: Tuple[int, ...] = (), dtype: str = "float32",
                    world: int = 1, axis: Optional[str] = None
                    ) -> "LeafLayout":
        kind = record.get("kind")
        return cls(path=tuple(path), kind=kind,
                   shape=tuple(int(s) for s in shape), dtype=str(dtype),
                   world=int(world), dim=record.get("dim"),
                   size=record.get("size"), axis=axis)


def layout_records(layouts: Sequence) -> List[dict]:
    """``to_record()`` over a layout sequence — accepts
    :class:`LeafLayout` objects or already-built record dicts
    (pass-through), so consumers can speak either form."""
    return [l.to_record() if isinstance(l, LeafLayout) else dict(l)
            for l in layouts]


def _record(spec) -> dict:
    return spec.to_record() if isinstance(spec, LeafLayout) else spec


# --------------------------------------------------------------------- #
# layout-table builders
# --------------------------------------------------------------------- #


def _leaf_paths(tree):
    from jax.tree_util import tree_flatten_with_path

    paths, _ = tree_flatten_with_path(tree)
    return paths


def _path_keys(path) -> Tuple[str, ...]:
    return tuple(str(k) for k in path)


def _leaf_meta(leaf) -> Tuple[Tuple[int, ...], str]:
    shape = tuple(int(s) for s in np.shape(leaf))
    dtype = getattr(leaf, "dtype", None)
    return shape, str(np.dtype(dtype) if dtype is not None
                      else np.asarray(leaf).dtype)


def _suffix_match(keys: Tuple[str, ...], table: Dict[Tuple[str, ...], Any]):
    """Longest matching path suffix, INCLUDING the empty suffix (a bare
    jax.Array params "tree" has the empty path as its only key) — the
    ``shard_opt_state`` discipline."""
    for start in range(len(keys) + 1):
        hit = table.get(keys[start:])
        if hit is not None:
            yield hit


def zero_opt_layouts(opt_state, params, world: int,
                     axis: Optional[str] = None) -> List[LeafLayout]:
    """Layout table for a WORLD-STACKED ZeRO-1/2 optimizer-state tree,
    in flattened-leaf order — the generalization of
    ``training.elastic._zero1_leaf_layout`` (which now delegates here):
    a ``(world, ceil(N/world))`` stack whose padded shard width matches
    a suffix-identified parameter is a ``shard``; any other leading
    member axis is a ``stack``; the rest are ``rep``.

    Shapes only — never materializes a leaf: multi-process-sharded
    arrays are not fully addressable and must not be pulled to host
    just to record their layout.
    """
    by_path: Dict[Tuple[str, ...], int] = {}
    for path, p in _leaf_paths(params):
        shape = tuple(np.shape(p))
        by_path[_path_keys(path)] = (
            int(np.prod(shape, dtype=np.int64)) if shape else 1)

    layouts: List[LeafLayout] = []
    for path, leaf in _leaf_paths(opt_state):
        shape, dtype = _leaf_meta(leaf)
        keys = _path_keys(path)
        spec: Optional[LeafLayout] = None
        if len(shape) == 2 and shape[0] == world:
            for n in _suffix_match(keys, by_path):
                if _ceil_div(n, world) == shape[1]:
                    spec = LeafLayout(keys, "shard", shape, dtype,
                                      world, size=n, axis=axis)
                    break
        if spec is None:
            kind = ("stack" if len(shape) >= 1 and shape[0] == world
                    else "rep")
            spec = LeafLayout(keys, kind, shape, dtype, world,
                              axis=axis if kind != "rep" else None)
        layouts.append(spec)
    return layouts


def _fsdp_param_layouts(params, dims, world: int,
                        axis: Optional[str]) -> List[LeafLayout]:
    import jax

    leaves_p, treedef = jax.tree_util.tree_flatten_with_path(params)
    dim_list = jax.tree.structure(params).flatten_up_to(dims)
    out = []
    for (path, leaf), d in zip(leaves_p, dim_list):
        shape, dtype = _leaf_meta(leaf)
        keys = _path_keys(path)
        if d is None:
            out.append(LeafLayout(keys, "rep", shape, dtype, world))
        else:
            out.append(LeafLayout(keys, "fsdp", shape, dtype, world,
                                  dim=int(d), axis=axis))
    del treedef
    return out


def _fsdp_opt_layouts(opt_state, params, dims, world: int,
                      axis: Optional[str]) -> List[LeafLayout]:
    """ZeRO-3 optimizer-state layouts: elementwise moments mirror their
    parameter leaf-for-leaf (``shard_opt_state``'s contract), so each
    state leaf inherits the dim of the suffix-identified param with an
    EQUAL shape; scalars and unmatched leaves replicate — never a
    shape-only guess (two same-shape params can shard different dims).
    """
    import jax

    by_path: Dict[Tuple[str, ...], Tuple[Tuple[int, ...], Optional[int]]] = {}
    dim_list = jax.tree.structure(params).flatten_up_to(dims)
    for (path, p), d in zip(_leaf_paths(params), dim_list):
        shape = tuple(int(s) for s in np.shape(p))
        by_path[_path_keys(path)] = (shape, None if d is None else int(d))

    out = []
    for path, leaf in _leaf_paths(opt_state):
        shape, dtype = _leaf_meta(leaf)
        keys = _path_keys(path)
        spec: Optional[LeafLayout] = None
        for pshape, d in _suffix_match(keys, by_path):
            if pshape == shape:
                if d is None:
                    spec = LeafLayout(keys, "rep", shape, dtype, world)
                else:
                    spec = LeafLayout(keys, "fsdp", shape, dtype, world,
                                      dim=d, axis=axis)
                break
        if spec is None:
            spec = LeafLayout(keys, "rep", shape, dtype, world)
        out.append(spec)
    return out


def state_layout_table(mode: str, params, opt_state=None, *, world: int,
                       dims=None, axis: Optional[str] = None
                       ) -> Dict[str, List[LeafLayout]]:
    """The per-mode layout table — the single source of truth the
    ISSUE's three consumers read:

    - plan-IR payload descriptors
      (``ops.plan_ir.describe_state_payload``),
    - elastic re-layout / shard-only snapshots (``topology_signature``
      stamps ``layout_records`` of these),
    - :class:`~chainermn_tpu.utils.programs.MemoryAccountant` gauges
      (``LeafLayout.local_bytes`` sums to the per-chip claim).

    Returns ``{"params": [...], "opt_state": [...]}`` in
    flattened-leaf order.  ``mode``:

    - ``"zero1"`` / ``"zero2"`` — params replicated, opt state the
      world-stacked flat-shard layout (:func:`zero_opt_layouts`;
      ZeRO-2's gradient shards are transient, never carried state);
    - ``"zero3"`` — params (and mirrored opt moments) dim-sharded per
      ``dims`` (an ``fsdp_dims`` tree — required).
    """
    if mode not in SHARDING_MODES:
        raise ValueError(
            f"unknown sharding mode {mode!r}; expected one of "
            f"{SHARDING_MODES}")
    world = int(world)
    if mode in ("zero1", "zero2"):
        table: Dict[str, List[LeafLayout]] = {"params": [
            LeafLayout(_path_keys(path), "rep", *(_leaf_meta(leaf)),
                       world)
            for path, leaf in _leaf_paths(params)]}
        if opt_state is not None:
            table["opt_state"] = zero_opt_layouts(
                opt_state, params, world, axis=axis)
        return table
    if dims is None:
        raise ValueError(
            "state_layout_table(mode='zero3') needs dims= (an "
            "fsdp_dims tree) — the shard dims ARE the layout")
    table = {"params": _fsdp_param_layouts(params, dims, world, axis)}
    if opt_state is not None:
        table["opt_state"] = _fsdp_opt_layouts(
            opt_state, params, dims, world, axis)
    return table


# --------------------------------------------------------------------- #
# host-side gather / scatter over any layout table
# --------------------------------------------------------------------- #


def gather_state_leaves(tree, layouts: Sequence):
    """Gather a sharded state tree to its full host-side values per its
    layout records: ``shard`` leaves → 1-D true-extent arrays,
    ``stack`` leaves → one representative row, ``fsdp``/``rep`` leaves
    unchanged (a ZeRO-3 leaf pulled to host via ``device_get`` is
    already full-width — the NamedSharding reassembles it).  The
    unified layer behind the deprecated ``gather_zero1_leaves``."""
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path

    from chainermn_tpu.training.elastic import RelayoutError

    path_leaves, treedef = tree_flatten_with_path(tree)
    if len(path_leaves) != len(layouts):
        raise RelayoutError(
            f"{len(layouts)} layout records for {len(path_leaves)} "
            "leaves")
    out = []
    for (path, leaf), spec in zip(path_leaves, layouts):
        rec = _record(spec)
        kind = rec.get("kind")
        arr = np.asarray(leaf)
        if kind == "shard":
            out.append(arr.reshape(-1)[: int(rec["size"])])
        elif kind == "stack":
            out.append(arr[0])
        elif kind in ("rep", "fsdp"):
            out.append(arr)
        else:
            raise RelayoutError(
                f"leaf {keystr(path)}: unknown layout kind {kind!r}")
    return jax.tree.unflatten(treedef, out)


def shard_state_leaves(tree, layouts: Sequence, world: int):
    """Inverse of :func:`gather_state_leaves`: lay a gathered state
    onto ``world`` members from scratch — ``shard`` leaves pad to
    ``ceil(N/world)·world`` and split contiguously, ``stack`` leaves
    re-stack, ``fsdp``/``rep`` leaves pass through (the DEVICE
    placement shards fsdp leaves; their host form is full-width).
    This is the reference layout ``relayout_state`` must match
    bitwise."""
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path

    from chainermn_tpu.training.elastic import RelayoutError

    path_leaves, treedef = tree_flatten_with_path(tree)
    if len(path_leaves) != len(layouts):
        raise RelayoutError(
            f"{len(layouts)} layout records for {len(path_leaves)} "
            "leaves")
    out = []
    for (path, leaf), spec in zip(path_leaves, layouts):
        rec = _record(spec)
        kind = rec.get("kind")
        arr = np.asarray(leaf)
        if kind == "shard":
            size = int(rec["size"])
            s = _ceil_div(size, int(world))
            flat = np.zeros((int(world) * s,), dtype=arr.dtype)
            flat[:size] = arr.reshape(-1)[:size]
            out.append(flat.reshape(int(world), s))
        elif kind == "stack":
            out.append(np.concatenate([arr[None]] * int(world), axis=0))
        elif kind in ("rep", "fsdp"):
            out.append(arr)
        else:
            raise RelayoutError(
                f"leaf {keystr(path)}: unknown layout kind {kind!r}")
    return jax.tree.unflatten(treedef, out)


# --------------------------------------------------------------------- #
# the JIT layer-gather stream (ZeRO-3's forward)
# --------------------------------------------------------------------- #


def _layer_groups(params, dims):
    """Split a param tree into gather units.  A mapping's top-level
    keys (sorted — deterministic across processes) are the layers; any
    other tree is one group.  Returns ``[(name, subtree, subdims)]``."""
    if isinstance(params, dict):
        names = sorted(params)
        return [(str(k), params[k], dims[k]) for k in names]
    return [("all", params, dims)]


class LayerGatherStream:
    """Just-in-time per-layer parameter gathers with a prefetch window
    — ZeRO-3's forward pass, built INSIDE the step's ``shard_map``.

    The canonical loop::

        stream = sharded.gather_stream(local_params, window=2)
        for i in range(len(stream)):
            full = stream.layer(i)        # this layer, full width
            x = apply(full, x)
            x = stream.retire(i, x)       # free it; release i+window

    Memory discipline: ``layer(i)`` issues the gathers for layers
    ``[i, i + window)``; each gather past the window is GATED — its
    input shards ride one ``lax.optimization_barrier`` with the retire
    token of layer ``i - window``, so XLA cannot hoist every gather to
    the program head and resident full-width params stay bounded by
    ``window`` layers.  ``retire(i, x)`` drops layer ``i``'s gathered
    leaves (XLA frees buffers with no remaining uses) and mints the
    token that releases layer ``i + window`` — threading ``x`` through
    the barrier, which transposes to itself, so the backward's
    reduce-scatter (the gather's AD transpose) is untouched.

    The gather itself is either the legacy per-leaf ``fsdp_gather`` or
    a tuned plan-IR program (``plan=``); gathers lowered from a
    CACHE-SERVED plan count ``sharded/plan_cache_gathers`` (and every
    issue counts ``sharded/layer_gathers``) — trace-time counters, one
    per compiled gather program, visible on ``/programz``.
    """

    def __init__(self, params, dims, *, axis_name: str,
                 window: int = 2, plan=None, wire_dtype=None,
                 inter_axis_name: Optional[str] = None,
                 plan_from_cache: bool = False):
        from chainermn_tpu.parallel.fsdp import fsdp_gather

        self._gather = fsdp_gather
        self._groups = _layer_groups(params, dims)
        self._axis_name = axis_name
        self._inter_axis_name = inter_axis_name
        self._window = max(1, int(window))
        self._plan = plan
        self._wire_dtype = wire_dtype
        self._plan_from_cache = bool(plan_from_cache)
        self._full: Dict[int, Any] = {}
        self._tokens: Dict[int, Any] = {}

    def __len__(self) -> int:
        return len(self._groups)

    @property
    def names(self) -> List[str]:
        return [name for name, _, _ in self._groups]

    @property
    def window(self) -> int:
        return self._window

    def _token0(self):
        import jax.numpy as jnp

        from chainermn_tpu.parallel._compat import pcast

        return pcast(jnp.zeros((), jnp.int32), self._axis_name,
                     to="varying")

    def _gate(self, subtree, token):
        """Tie every shard leaf's availability to ``token`` — the
        scheduling fence that keeps the gather inside the window."""
        import jax

        from chainermn_tpu.ops.plan_ir import _pin

        if token is None:
            return subtree
        leaves, treedef = jax.tree.flatten(subtree)
        pinned = _pin(tuple(leaves) + (token,))
        return treedef.unflatten(list(pinned[:-1]))

    def _issue(self, i: int) -> None:
        if i in self._full:
            return
        from chainermn_tpu.utils.metrics import get_registry

        name, subtree, subdims = self._groups[i]
        gate = self._tokens.get(i - self._window)
        subtree = self._gate(subtree, gate)
        reg = get_registry()
        reg.inc("sharded/layer_gathers")
        if self._plan_from_cache:
            reg.inc("sharded/plan_cache_gathers")
        self._full[i] = self._gather(
            subtree, subdims, self._axis_name,
            None if self._plan is not None else self._wire_dtype,
            plan=self._plan, inter_axis_name=self._inter_axis_name)

    def layer(self, i: int):
        """The full-width params of layer ``i``; issues (prefetches)
        gathers for layers ``[i, i + window)`` whose release token
        already exists."""
        n = len(self._groups)
        if not 0 <= i < n:
            raise IndexError(f"layer {i} of {n}")
        self._issue(i)
        for j in range(i + 1, min(i + self._window, n)):
            if j - self._window < 0 or j - self._window in self._tokens:
                self._issue(j)
        return self._full[i]

    def retire(self, i: int, x):
        """Drop layer ``i``'s gathered params and mint the token that
        releases layer ``i + window``'s gather; returns ``x`` (threaded
        through the barrier — use the returned value)."""
        from chainermn_tpu.ops.plan_ir import _pin

        self._full.pop(i, None)
        pinned = _pin((x, self._token0()))
        x, token = pinned
        self._tokens[i] = token
        return x


# --------------------------------------------------------------------- #
# the ZeRO-3 plan
# --------------------------------------------------------------------- #


class ShardedState:
    """ZeRO-3/FSDP sharded-state plan over one data axis: params and
    their elementwise optimizer state live 1/world at rest
    (``fsdp_dims``/``fsdp_specs`` pick the layout), are gathered
    just-in-time per layer (:meth:`gather_stream`), and the gather
    lowers through a TUNED plan-IR program (:meth:`tune_gather_plan`)
    whose payload descriptors come straight off the layout table.

    Usage (the ``tests/parallel_tests/test_sharded_state.py`` drill)::

        sharded = ShardedState(params, comm)
        params = sharded.place(params)             # 1/world at rest
        opt_state = sharded.init_opt_state(tx)     # moments mirror it
        sharded.tune_gather_plan(comm)             # cached plan-IR
        # inside shard_map(in_specs=(sharded.specs, ...)):
        stream = sharded.gather_stream(local_params)

    The layout signature is the single source of truth three ways:
    :meth:`layouts` feeds ``topology_signature(sharding="zero3")`` (so
    elastic resize and shard-only snapshots re-lay this state),
    :meth:`payload_descs` generates the plan-IR payload for the tuner,
    and :meth:`register_memory` wires the placed state into the
    memory accountant so the per-chip win is measured, not asserted
    (``memory/<prefix>_params_bytes`` counts replication N× — see
    ``programs._leaf_bytes``).
    """

    def __init__(self, params, comm=None, *, mesh=None,
                 axis_name: Optional[str] = None, base_specs=None,
                 min_size: int = 2, wire_dtype=None,
                 window: Optional[int] = None):
        import jax

        from chainermn_tpu.parallel.fsdp import fsdp_dims, fsdp_specs
        from chainermn_tpu.utils import autotune

        if comm is not None:
            mesh = mesh if mesh is not None else comm.mesh
            axis_name = axis_name or comm.axis_name
        if mesh is None or axis_name is None:
            raise ValueError("ShardedState needs comm, or mesh + "
                             "axis_name")
        self.mesh = mesh
        self.axis_name = axis_name
        names = list(mesh.axis_names)
        shape = tuple(int(s) for s in np.asarray(mesh.devices).shape)
        self.world = int(shape[names.index(axis_name)])
        self.wire_dtype = wire_dtype
        self.dims = fsdp_dims(params, self.world, base_specs,
                              min_size=min_size, axis=axis_name)
        self.specs = fsdp_specs(params, self.dims, axis=axis_name,
                                base_specs=base_specs)
        self.window = 2 if window is None else max(1, int(window))
        self.plan_cell = autotune.PlanCell()
        self.params = None          # set by place()
        self.opt_state = None       # set by init_opt_state()
        self._template_meta = [
            _leaf_meta(leaf) for leaf in jax.tree.leaves(params)]
        self._treedef = jax.tree.structure(params)

    # -- placement ------------------------------------------------------ #

    def place(self, params):
        """Device-put ``params`` into the at-rest 1/world layout; the
        placed tree is kept as the accountant's root."""
        import jax
        from jax.sharding import NamedSharding

        placed = jax.tree.map(
            lambda p, s: jax.device_put(p, NamedSharding(self.mesh, s)),
            params, self.specs)
        self.params = placed
        return placed

    def init_opt_state(self, optimizer):
        """Optimizer state pinned to the params' shardings
        (``shard_opt_state`` — elementwise moments mirror the layout);
        requires :meth:`place` first."""
        from chainermn_tpu.training.optimizers import shard_opt_state

        if self.params is None:
            raise RuntimeError("init_opt_state before place(params)")
        self.opt_state = shard_opt_state(optimizer, self.params)
        return self.opt_state

    # -- the signature --------------------------------------------------- #

    def layouts(self, opt_state=None) -> Dict[str, List[LeafLayout]]:
        params = self.params
        if params is None:
            params = self._treedef.unflatten([
                np.zeros(shape, dtype)
                for shape, dtype in self._template_meta])
        return state_layout_table(
            "zero3", params,
            opt_state if opt_state is not None else self.opt_state,
            world=self.world, dims=self.dims, axis=self.axis_name)

    def payload_descs(self):
        """Plan-IR payload descriptors for the LOCAL shard payload the
        gather moves — derived from the layout table, never from live
        arrays (``ops.plan_ir.describe_state_payload``)."""
        from chainermn_tpu.ops import plan_ir

        return plan_ir.describe_state_payload(
            self.layouts()["params"], self.world)

    def local_template(self):
        """A host tree shaped like one member's at-rest shard — the
        tuner's payload template (values never read)."""
        descs = self.payload_descs()
        return self._treedef.unflatten([
            np.zeros(d.shape, d.dtype) for d in descs])

    def local_bytes(self, world: Optional[int] = None) -> int:
        """Analytic at-rest param+opt bytes PER CHIP from the layout
        table (the accountant measures; this predicts)."""
        table = self.layouts()
        total = sum(l.local_bytes(world) for l in table["params"])
        total += sum(l.local_bytes(world)
                     for l in table.get("opt_state", []))
        return total

    # -- the tuned gather ------------------------------------------------ #

    def tune_gather_plan(self, comm, *, cache_path: Optional[str] = None,
                         wire_dtypes: Optional[Sequence] = None,
                         **tune_kw):
        """Tune (or cache warm-start) the ``fsdp_gather`` plan-IR
        program for this layout — ``autotune_pattern_plan`` over the
        payload :meth:`payload_descs` describes, keyed so sharded-state
        plans never serve a foreign ``fsdp_gather`` call site.  The
        winner lands in :attr:`plan_cell` (generation-bumped, drift-
        guarded — the ``StandardUpdater`` contract)."""
        from chainermn_tpu.utils import autotune

        if wire_dtypes is None:
            wire_dtypes = ((None,) if self.wire_dtype is None
                           else (None, self.wire_dtype))
        kwargs = dict(
            pattern="fsdp_gather",
            dims=self.dims,
            wire_dtypes=tuple(wire_dtypes),
            cache_path=cache_path,
            variant_extra={"consumer": "sharded_state/zero3",
                           "window": int(self.window)},
            **tune_kw)
        plan = autotune.autotune_pattern_plan(
            comm, self.local_template(), **kwargs)
        self.plan_cell.resolve(plan)
        self.plan_cell.tuner = autotune.autotune_pattern_plan
        self.plan_cell.tune_kwargs = kwargs
        return plan

    def auto_window(self, layer_compute_s: float,
                    max_window: int = 4) -> int:
        """Size the prefetch window from the tuned plan's measured link
        constants and a per-layer compute time
        (``utils.comm_model.choose_gather_prefetch_depth``); adopts and
        returns the chosen depth."""
        from chainermn_tpu.utils import comm_model

        plan = self.plan_cell.plan
        link = None
        if plan is not None and plan.link:
            link = comm_model.LinkParams(**plan.link)
        n_groups = max(1, len(_layer_groups(
            self.local_template(), self.dims)))
        per_layer = self.local_bytes() * self.world / n_groups
        self.window = comm_model.choose_gather_prefetch_depth(
            per_layer, self.world, layer_compute_s, link=link,
            max_window=max_window)
        return self.window

    # -- in-step surface ------------------------------------------------- #

    def gather(self, local_params, *, plan="cell"):
        """One whole-tree just-in-time gather (no layer streaming) —
        ``fsdp_gather`` through the tuned program when one is
        resolved.  Call INSIDE shard_map."""
        from chainermn_tpu.parallel.fsdp import fsdp_gather

        resolved = self.plan_cell.plan if plan == "cell" else plan
        return fsdp_gather(
            local_params, self.dims, self.axis_name,
            None if resolved is not None else self.wire_dtype,
            plan=resolved)

    def gather_stream(self, local_params, *, window: Optional[int] = None,
                      plan="cell") -> LayerGatherStream:
        """A :class:`LayerGatherStream` over this layout — the ZeRO-3
        forward.  Call INSIDE shard_map, once per step trace."""
        resolved = self.plan_cell.plan if plan == "cell" else plan
        from_cache = bool(getattr(resolved, "from_cache", False))
        return LayerGatherStream(
            local_params, self.dims, axis_name=self.axis_name,
            window=self.window if window is None else window,
            plan=resolved, wire_dtype=self.wire_dtype,
            plan_from_cache=from_cache)

    # -- accounting ------------------------------------------------------ #

    def register_memory(self, accountant=None,
                        prefix: str = "sharded") -> None:
        """Register the placed state's device roots with the memory
        accountant (``memory/<prefix>_params_bytes`` /
        ``memory/<prefix>_opt_state_bytes`` gauges) — weakref-held, so
        a retired plan samples as 0."""
        from chainermn_tpu.utils.programs import (
            get_accountant,
            weakref_root,
        )

        acc = accountant if accountant is not None else get_accountant()
        acc.register(f"{prefix}_params", weakref_root(self, "params"))
        acc.register(f"{prefix}_opt_state",
                     weakref_root(self, "opt_state"))
