"""Ring attention — context parallelism for long sequences.

Absent from the reference (SURVEY.md §5: "long-context — absent, predates
it"); first-class here per the task spec.  Design (blockwise ring):

- the sequence is sharded over the ``seq`` mesh axis: device ``r`` holds
  Q/K/V for tokens ``[r·T_blk, (r+1)·T_blk)``;
- K/V blocks rotate around the ICI ring (``lax.ppermute`` neighbour
  copies) for ``S`` steps while each device's resident Q accumulates
  attention against every block with a numerically-stable *online
  softmax* (running max ``m``, normaliser ``den``, numerator ``num`` —
  the flash-attention recurrence, so no (T, T_full) score matrix ever
  materialises);
- compute and the next block's transfer overlap: inside ``lax.scan`` XLA
  schedules the ppermute concurrently with the einsums (the double-
  buffering the reference built from CUDA streams falls out of the
  compiler here);
- backward is the transpose of (scan ∘ ppermute ∘ online-softmax):
  autodiff derives the reverse ring — no hand-written backward pass.

Memory: O(T_blk · T_blk) per step instead of O(T · T); comm volume per
device per step is one K/V block — the all-gather-free property that makes
context length scale linearly with ring size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "local_attention", "zigzag_indices",
           "broadcast_kv"]

_NEG = -1e30  # finite mask value: keeps the online-softmax max well-defined


def _group_rep(q_heads: int, kv_heads: int) -> int:
    if q_heads % kv_heads:
        raise ValueError(
            f"query heads {q_heads} not a multiple of kv heads {kv_heads}")
    return q_heads // kv_heads


def broadcast_kv(k, v, rep: int):
    """Broadcast shared K/V heads to query width for kernels that want
    matching head counts.  The interleave convention (head ``g`` repeated
    ``rep`` times consecutively) is THE grouping invariant — it must match
    :func:`_qk_scores`'s ``h // rep`` mapping; keep every call site on
    this helper."""
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _qk_scores(q, k):
    """``(B,T,H,D) × (B,S,G,D) -> (B,H,T,S)`` scores; when ``G < H``
    (GQA/MQA) query head ``h`` reads kv head ``h // (H/G)`` via a grouped
    einsum — the shared K is never materialised at query width."""
    H, G = q.shape[2], k.shape[2]
    if H == G:
        return jnp.einsum("bthd,bshd->bhts", q, k)
    R = _group_rep(H, G)
    B, T, _, D = q.shape
    s = jnp.einsum("btgrd,bsgd->bgrts", q.reshape(B, T, G, R, D), k)
    return s.reshape(B, H, T, -1)


def _pv_mix(p, v):
    """``(B,H,T,S) × (B,S,G,D) -> (B,H,T,D)`` value mix, grouped when
    ``G < H`` (the dual of :func:`_qk_scores`)."""
    H, G = p.shape[1], v.shape[2]
    if H == G:
        return jnp.einsum("bhts,bshd->bhtd", p, v)
    R = _group_rep(H, G)
    B, _, T, S = p.shape
    o = jnp.einsum("bgrts,bsgd->bgrtd", p.reshape(B, G, R, T, S), v)
    return o.reshape(B, H, T, -1)


def local_attention(q, k, v, *, causal: bool = False, window=None,
                    q_offset=0, k_offset=0):
    """Plain softmax attention on local blocks (the S=1 degenerate case and
    the reference oracle for tests).  ``q: (B, T, H, D)``; ``k``/``v`` may
    carry fewer (shared) heads ``(B, S, G, D)`` with ``G | H`` (GQA).
    ``window``: sliding causal window — token t attends to
    ``(t-window, t]`` (requires ``causal``)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    scale = q.shape[-1] ** -0.5
    s = _qk_scores(q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        allow = qpos[:, None] >= kpos[None, :]
        if window is not None:
            allow &= (qpos[:, None] - kpos[None, :]) < window
        s = jnp.where(allow[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return _pv_mix(p, v).transpose(0, 2, 1, 3)


def _lse_attention_pair(q, kb, vb, *, causal, q_offset, k_offset,
                        window=None):
    """XLA computation of one (Q block × K/V block) partial with its
    log-sum-exp — semantics identical to
    ``flash_attention(..., return_lse=True)`` including the fully-masked
    convention (o=0, lse≈-1e30).  Used by the ring schedule on backends
    where the Pallas interpreter cannot discharge seq-varying traced
    SMEM scalars under shard_map's vma checking (jax interpreter bug);
    on TPU the real kernel runs instead."""
    scale = q.shape[-1] ** -0.5
    s = _qk_scores(q.astype(jnp.float32),
                   kb.astype(jnp.float32)) * scale
    allow = None
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(kb.shape[1])
        allow = qpos[:, None] >= kpos[None, :]
        if window is not None:
            allow &= (qpos[:, None] - kpos[None, :]) < window
        allow = allow[None, None]
        s = jnp.where(allow, s, _NEG)
    m = s.max(axis=-1)                                   # (B,H,T)
    p = jnp.exp(s - m[..., None])
    if allow is not None:
        p = jnp.where(allow, p, 0.0)
    l = p.sum(axis=-1)
    safe = jnp.maximum(l, 1e-30)
    o = _pv_mix(p, vb.astype(jnp.float32)) / safe[..., None]   # (B,H,T,D)
    lse = m + jnp.log(safe)                              # (B,H,T)
    return (o.transpose(0, 2, 1, 3).astype(q.dtype),
            lse.transpose(0, 2, 1))                      # (B,T,H,D),(B,T,H)


def zigzag_indices(S: int, T_global: int):
    """Global-sequence permutation for the load-balanced causal layout.

    Device ``r`` of an ``S``-ring holds chunks ``r`` and ``2S−1−r`` of the
    ``2S``-chunk global sequence (Striped/zigzag ring attention): each
    device then owns one "early" and one mirrored "late" chunk, so under
    causal masking every (device, visiting block) pair carries ~half the
    score matrix — the causal FLOP saving becomes a *wall-clock* saving
    because no device idles while another computes a dense pair (the
    contiguous layout's skipped-future blocks save FLOPs but the ring
    still waits on its busiest device each step).

    Returns an ``(S, T_global // S)`` int array: row ``r`` = the global
    token indices device ``r`` holds, in local order.  Feed
    ``x[..., zigzag_indices(S, T)[r], :]`` per device (or gather through
    the flattened permutation before sharding) and pass
    ``layout="zigzag"`` to :func:`ring_attention`.
    """
    import numpy as np

    if T_global % (2 * S):
        raise ValueError(
            f"zigzag layout needs T ({T_global}) divisible by 2*S ({2*S})")
    C = T_global // (2 * S)
    rows = []
    for rr in range(S):
        rows.append(np.concatenate([
            np.arange(rr * C, (rr + 1) * C),
            np.arange((2 * S - 1 - rr) * C, (2 * S - rr) * C)]))
    return np.stack(rows)


def _block_offsets(rr, T, S, layout):
    """Global offsets of the contiguous runs making up rank ``rr``'s
    block: one T-run (contiguous) or two T/2-runs (zigzag)."""
    if layout == "contiguous":
        return [(0, T, rr * T)]
    C = T // 2
    return [(0, C, rr * C), (C, C, (2 * S - 1 - rr) * C)]


def _block_positions(rr, T, S, layout):
    parts = [off + jnp.arange(ln) for _, ln, off in
             _block_offsets(rr, T, S, layout)]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def _rotate_kv(k_blk, v_blk, axis_name, ring, plan):
    """One ring rotation of the visiting K/V pair — raw ppermutes, or
    the collective-plan IR lowering when a tuned ``ring_permute`` plan
    is supplied (separate-vs-fused ppermute candidates)."""
    if plan is None:
        return (lax.ppermute(k_blk, axis_name, perm=ring),
                lax.ppermute(v_blk, axis_name, perm=ring))
    from chainermn_tpu.ops import plan_ir

    k_blk, v_blk = plan_ir.lower_ring_permute(
        plan_ir.ensure_program(plan, "ring_permute"), (k_blk, v_blk),
        axis_name=axis_name)
    return k_blk, v_blk


def ring_attention(q, k, v, *, axis_name: str = "seq",
                   causal: bool = False, window=None, remat: bool = True,
                   use_flash: bool = False, block_q: int = 1024,
                   block_k: int = 1024, bwd_block_q=None,
                   bwd_block_k=None, interpret: bool = False,
                   layout: str = "contiguous", permute_plan=None):
    """Blockwise ring attention.  Call INSIDE ``shard_map`` over
    ``axis_name`` with Q/K/V sequence-sharded: ``(B, T_blk, H, D)`` each.

    Args:
      causal: autoregressive masking in *global* token positions (block
        offsets are derived from the ring rank, so the result equals
        full-sequence causal attention).
      remat: rematerialise each block step in backward (grads recompute
        the blockwise forward instead of storing per-step products).
      use_flash: compute each (local Q × visiting K/V) pair with the
        Pallas flash kernel (:mod:`chainermn_tpu.ops.pallas_attention`)
        instead of XLA einsums; per-pair partials ``(o_i, lse_i)`` are
        merged exactly in log-space.  The traced block offsets ride to
        the kernel in SMEM.  Requires
        ``flash_attention_supported(T_blk, T_blk, block_q, block_k)``.
      interpret: run the flash kernel in the Pallas interpreter
        (non-TPU backends).
      layout: ``"contiguous"`` (device ``r`` holds tokens
        ``[r·T, (r+1)·T)``) or ``"zigzag"`` (device ``r`` holds chunks
        ``r`` and ``2S−1−r`` — see :func:`zigzag_indices`; balances the
        causal workload across the ring so the 2× FLOP saving is also a
        wall-clock saving).
      permute_plan: a tuned Plan from
        ``autotune_pattern_plan(pattern="ring_permute")``, its
        ``.program`` dict, or an ``ops.plan_ir.PlanProgram`` — lowers
        the per-step K/V rotation through the collective-plan IR
        (separate-vs-fused ppermute candidates) instead of the two raw
        ``lax.ppermute`` calls.

    Returns ``(B, T_blk, H, D)`` — this device's attended block.

    GQA/MQA: ``k``/``v`` may carry fewer (shared) heads than ``q``
    (``G | H``).  The ring then rotates K/V at their natural ``G``-head
    width — the ICI traffic and resident K/V memory shrink by ``H/G`` —
    and the per-pair compute reads the shared heads through grouped
    einsums (XLA path) or a local per-block broadcast (kernel path).
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout {layout!r} not in (contiguous, zigzag)")
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    _group_rep(H, k.shape[2])  # validate G | H before tracing the ring
    scale = D ** -0.5
    ring = [(i, (i + 1) % S) for i in range(S)]
    if layout == "zigzag" and T % 2:
        raise ValueError(f"zigzag needs an even local length, got {T}")

    # windowed contiguous causal rings: visiting blocks more than
    # ceil(W/T) positions behind are entirely out-of-window, and blocks
    # ahead are entirely future — truncate the ring statically instead
    # of rotating and masking S-1 times (zigzag keeps all steps: each
    # device also holds a mirrored late chunk whose window reaches far)
    n_steps = S
    if window is not None and causal and layout == "contiguous":
        n_steps = min(S, -(-window // T) + 1)

    if use_flash:
        return _ring_flash(q, k, v, axis_name=axis_name, causal=causal,
                           window=window,
                           remat=remat, block_q=block_q, block_k=block_k,
                           bwd_block_q=bwd_block_q,
                           bwd_block_k=bwd_block_k,
                           interpret=interpret, S=S, r=r, ring=ring,
                           layout=layout, n_steps=n_steps,
                           permute_plan=permute_plan)

    def block_step(carry, i):
        k_blk, v_blk, num, den, m = carry
        src = (r - i) % S  # which block this device currently holds
        s = _qk_scores(q, k_blk) * scale
        if causal:
            qpos = _block_positions(r, T, S, layout)
            kpos = _block_positions(src, T, S, layout)
            allow = qpos[:, None] >= kpos[None, :]
            if window is not None:
                allow &= (qpos[:, None] - kpos[None, :]) < window
            s = jnp.where(allow[None, None], s, _NEG)
        # online softmax update (flash recurrence)
        m_new = jnp.maximum(m, s.max(axis=-1))           # (B,H,T)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])                # (B,H,T,S)
        num = num * alpha[..., None] + _pv_mix(p, v_blk)
        den = den * alpha + p.sum(axis=-1)
        # rotate K/V to the next device; XLA overlaps this with the math
        if S > 1:
            k_blk, v_blk = _rotate_kv(k_blk, v_blk, axis_name, ring,
                                      permute_plan)
        return (k_blk, v_blk, num, den, m_new), None

    step = jax.checkpoint(block_step) if remat else block_step

    # initial accumulators are zeros that must carry the UNION of q's
    # varying axes (q is seq-sharded, so the ring axis is always present;
    # under composition it may vary over data/model/pipe too) — deriving
    # them from q inherits the vma, and the multiply folds away in XLA
    zq = (q * 0).transpose(0, 2, 1, 3)
    num0 = zq                                            # (B,H,T,D)
    den0 = zq[..., 0]                                    # (B,H,T)
    m0 = den0 + jnp.asarray(_NEG, q.dtype)
    (k, v, num, den, m), _ = lax.scan(
        step, (k, v, num0, den0, m0), jnp.arange(n_steps))
    out = num / den[..., None]                           # (B,H,T,D)
    return out.transpose(0, 2, 1, 3)                     # (B,T,H,D)


def _merge_lse(o, lse, o_i, lse_i):
    """Exact log-space merge of two attention partials."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_new = jnp.exp(lse_i - lse_new)[..., None]
    return o * w_old + o_i * w_new, lse_new


def _ring_flash(q, k, v, *, axis_name, causal, window, remat, block_q,
                block_k, interpret, S, r, ring, bwd_block_q=None,
                bwd_block_k=None, layout="contiguous", n_steps=None,
                permute_plan=None):
    """Ring schedule with the Pallas kernel as the per-pair compute.

    Every visiting K/V block is attended with the SAME kernel call,
    parameterised by the *global* block offsets (``q_offset = r·T``,
    ``k_offset = src·T`` ride to the kernel in SMEM as traced scalars).
    The kernel's own ``pl.when(needed)`` grid predicate then skips the
    matmuls of every fully-future K block — so a visiting block from a
    later ring position costs ~zero FLOPs and yields the neutral partial
    ``(o=0, lse≈-1e30)``, preserving the ring's 2× causal saving without
    any select-and-discard on the host side.

    Per-pair partials ``(o_i, lse_i)`` merge exactly in log-space:
    ``lse = logaddexp(lse, lse_i)``, ``o = o·e^{lse_prev−lse} +
    o_i·e^{lse_i−lse}``.  Autodiff differentiates the merge; the
    kernel's custom VJP covers ``∂(o_i, lse_i)/∂(q, k, v)``.

    The ring itself is a ``lax.scan`` (compile time independent of ring
    size); XLA overlaps each step's ppermute with the kernel math.
    """
    from chainermn_tpu.ops.pallas_attention import flash_attention

    T = q.shape[1]
    # GQA: the ring rotates K/V at shared-head width; the Pallas kernel
    # wants matching head counts, so broadcast the *local visiting block*
    # to query width at the kernel boundary (a per-block, post-ppermute
    # expansion — the wire and the carry stay at G heads).  The XLA
    # interpret pair reads shared heads directly via grouped einsums.
    rep = _group_rep(q.shape[2], k.shape[2])

    if interpret:
        # the Pallas hlo-interpreter cannot discharge seq-varying traced
        # SMEM scalars under shard_map's vma checking — run the
        # semantically-identical XLA pair instead (the kernel itself is
        # covered standalone by the ops tests; TPU runs the real kernel)
        def pair(qq, kb, vb, q_off, k_off):
            return _lse_attention_pair(
                qq, kb, vb, causal=causal, window=window,
                q_offset=q_off, k_offset=k_off)
    else:
        def pair(qq, kb, vb, q_off, k_off):
            kb, vb = broadcast_kv(kb, vb, rep)
            return flash_attention(
                qq, kb, vb, causal=causal, window=window,
                q_offset=q_off, k_offset=k_off,
                block_q=block_q, block_k=block_k,
                bwd_block_q=bwd_block_q, bwd_block_k=bwd_block_k,
                return_lse=True,
                interpret=False)

    def attend_block(k_blk, v_blk, src):
        """Full local Q against the visiting block: one kernel call per
        (contiguous Q run × contiguous K run) — 1 for the contiguous
        layout, 4 for zigzag — merged exactly in log-space."""
        k_runs = _block_offsets(src, T, S, layout)
        outs = []
        for q_start, q_len, q_off in _block_offsets(r, T, S, layout):
            qq = lax.dynamic_slice_in_dim(q, q_start, q_len, axis=1)
            o_h = lse_h = None
            for k_start, k_len, k_off in k_runs:
                kb = lax.dynamic_slice_in_dim(k_blk, k_start, k_len, 1)
                vb = lax.dynamic_slice_in_dim(v_blk, k_start, k_len, 1)
                o_i, lse_i = pair(qq, kb, vb, q_off, k_off)
                o_i = o_i.astype(jnp.float32)
                if o_h is None:
                    o_h, lse_h = o_i, lse_i
                else:
                    o_h, lse_h = _merge_lse(o_h, lse_h, o_i, lse_i)
            outs.append((o_h, lse_h))
        if len(outs) == 1:
            return outs[0]
        return (jnp.concatenate([o for o, _ in outs], axis=1),
                jnp.concatenate([l for _, l in outs], axis=1))

    if n_steps is None:
        n_steps = S
    # step 0: self block
    o, lse = attend_block(k, v, r)
    if n_steps == 1:
        return o.astype(q.dtype)

    def block_step(carry, i):
        k_blk, v_blk, o, lse = carry
        k_blk, v_blk = _rotate_kv(k_blk, v_blk, axis_name, ring,
                                  permute_plan)
        src = (r - i) % S                                # block now held
        o_i, lse_i = attend_block(k_blk, v_blk, src)
        o, lse = _merge_lse(o, lse, o_i, lse_i)
        return (k_blk, v_blk, o, lse), None

    step = jax.checkpoint(block_step) if remat else block_step
    (k, v, o, lse), _ = lax.scan(
        step, (k, v, o, lse), jnp.arange(1, n_steps))
    return o.astype(q.dtype)
