"""Ring attention — context parallelism for long sequences.

Absent from the reference (SURVEY.md §5: "long-context — absent, predates
it"); first-class here per the task spec.  Design (blockwise ring):

- the sequence is sharded over the ``seq`` mesh axis: device ``r`` holds
  Q/K/V for tokens ``[r·T_blk, (r+1)·T_blk)``;
- K/V blocks rotate around the ICI ring (``lax.ppermute`` neighbour
  copies) for ``S`` steps while each device's resident Q accumulates
  attention against every block with a numerically-stable *online
  softmax* (running max ``m``, normaliser ``den``, numerator ``num`` —
  the flash-attention recurrence, so no (T, T_full) score matrix ever
  materialises);
- compute and the next block's transfer overlap: inside ``lax.scan`` XLA
  schedules the ppermute concurrently with the einsums (the double-
  buffering the reference built from CUDA streams falls out of the
  compiler here);
- backward is the transpose of (scan ∘ ppermute ∘ online-softmax):
  autodiff derives the reverse ring — no hand-written backward pass.

Memory: O(T_blk · T_blk) per step instead of O(T · T); comm volume per
device per step is one K/V block — the all-gather-free property that makes
context length scale linearly with ring size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "local_attention"]

_NEG = -1e30  # finite mask value: keeps the online-softmax max well-defined


def local_attention(q, k, v, *, causal: bool = False, q_offset=0,
                    k_offset=0):
    """Plain softmax attention on local blocks (the S=1 degenerate case and
    the reference oracle for tests).  Shapes ``(B, T, H, D)``."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        allow = qpos[:, None] >= kpos[None, :]
        s = jnp.where(allow[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def ring_attention(q, k, v, *, axis_name: str = "seq",
                   causal: bool = False, remat: bool = True):
    """Blockwise ring attention.  Call INSIDE ``shard_map`` over
    ``axis_name`` with Q/K/V sequence-sharded: ``(B, T_blk, H, D)`` each.

    Args:
      causal: autoregressive masking in *global* token positions (block
        offsets are derived from the ring rank, so the result equals
        full-sequence causal attention).
      remat: rematerialise each block step in backward (grads recompute
        the blockwise forward instead of storing per-step products).

    Returns ``(B, T_blk, H, D)`` — this device's attended block.
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = D ** -0.5
    ring = [(i, (i + 1) % S) for i in range(S)]

    def block_step(carry, i):
        k_blk, v_blk, num, den, m = carry
        src = (r - i) % S  # which block this device currently holds
        s = jnp.einsum("bthd,bshd->bhts", q, k_blk) * scale
        if causal:
            qpos = r * T + jnp.arange(T)
            kpos = src * T + jnp.arange(T)
            allow = qpos[:, None] >= kpos[None, :]
            s = jnp.where(allow[None, None], s, _NEG)
        # online softmax update (flash recurrence)
        m_new = jnp.maximum(m, s.max(axis=-1))           # (B,H,T)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])                # (B,H,T,S)
        num = num * alpha[..., None] + jnp.einsum(
            "bhts,bshd->bhtd", p, v_blk)
        den = den * alpha + p.sum(axis=-1)
        # rotate K/V to the next device; XLA overlaps this with the math
        if S > 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm=ring)
            v_blk = lax.ppermute(v_blk, axis_name, perm=ring)
        return (k_blk, v_blk, num, den, m_new), None

    step = jax.checkpoint(block_step) if remat else block_step

    # initial accumulators are zeros that must carry the UNION of q's
    # varying axes (q is seq-sharded, so the ring axis is always present;
    # under composition it may vary over data/model/pipe too) — deriving
    # them from q inherits the vma, and the multiply folds away in XLA
    zq = (q * 0).transpose(0, 2, 1, 3)
    num0 = zq                                            # (B,H,T,D)
    den0 = zq[..., 0]                                    # (B,H,T)
    m0 = den0 + jnp.asarray(_NEG, q.dtype)
    (k, v, num, den, m), _ = lax.scan(
        step, (k, v, num0, den0, m0), jnp.arange(S))
    out = num / den[..., None]                           # (B,H,T,D)
    return out.transpose(0, 2, 1, 3)                     # (B,T,H,D)
