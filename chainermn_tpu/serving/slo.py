"""SLO reporting over serving request records.

The engine timestamps every request lifecycle (submit → admit → first
token → eviction) and :meth:`~chainermn_tpu.serving.ServingEngine.
request_records` exposes the derived per-request latencies.  This
module turns those records into the report a serving operator actually
reads: per-arm p50/p9x for queue wait, TTFT, TPOT and end-to-end
latency, on the shared :class:`~chainermn_tpu.utils.metrics.Histogram`
lattice — the same percentile math the metrics registry, the
Prometheus exposition and ``bench_serving`` use, so the number on the
dashboard IS the number in the bench JSON (small request counts ride
the histogram's exact-sample path, which is numpy-``linear``
identical; ``bench_serving`` asserts that equivalence every run).

"Arms" are whatever populations are being compared: scheduling modes
(continuous vs gang), model variants, deployment slices.  One arm is
fine too.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Sequence

from chainermn_tpu.utils.metrics import Histogram

__all__ = ["SLOReport"]

_FIELDS = ("queue_wait", "ttft", "tpot", "e2e")


def _field(record, name: str) -> Optional[float]:
    if isinstance(record, dict):
        return record.get(name)
    return getattr(record, name, None)


class SLOReport:
    """Per-arm latency percentiles from request records.

    Args:
      percentiles: which percentiles :meth:`summary` reports
        (``p<q>`` keys; default p50/p95/p99).

    Use::

        slo = SLOReport()
        slo.add_arm("continuous", engine.request_records())
        print(slo.render())            # the operator table (ms)
        slo.summary()["continuous"]["ttft"]["p99"]   # seconds
    """

    def __init__(self, percentiles: Sequence[float] = (50, 95, 99)):
        self.percentiles = tuple(percentiles)
        self._arms: Dict[str, Dict[str, Histogram]] = {}

    def add_arm(self, name: str, records: Iterable) -> "SLOReport":
        """Fold ``records`` (``Completion``s, or dicts with the same
        field names) into arm ``name``'s histograms; repeated calls
        accumulate.  Returns self for chaining."""
        hists = self._arms.setdefault(
            name, {f: Histogram() for f in _FIELDS})
        for rec in records:
            for f in _FIELDS:
                v = _field(rec, f)
                if v is not None:
                    hists[f].observe(float(v))
        return self

    @property
    def arms(self):
        return tuple(self._arms)

    def histograms(self, arm: str) -> Dict[str, Histogram]:
        """The arm's per-field lattice histograms (mergeable /
        exportable through ``utils.metrics`` like any other)."""
        return dict(self._arms[arm])

    def summary(self) -> dict:
        """``{arm: {field: {count, mean, p50, ..., max}}}``, seconds."""
        out = {}
        for arm, hists in self._arms.items():
            out[arm] = {}
            for f, h in hists.items():
                row = {"count": h.count, "mean": h.mean, "max": h.max}
                for q in self.percentiles:
                    row[f"p{q:g}"] = h.percentile(q)
                out[arm][f] = row
        return out

    def to_dict(self) -> dict:
        return {"percentiles": list(self.percentiles),
                "arms": self.summary()}

    def write_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=float)
        return path

    def render(self) -> str:
        """The printable table, milliseconds (TPOT included — it is a
        latency too, just per token)."""
        cols = ["arm", "metric", "n", "mean_ms"] + \
            [f"p{q:g}_ms" for q in self.percentiles] + ["max_ms"]
        rows = []
        for arm, fields in self.summary().items():
            for f in _FIELDS:
                s = fields[f]

                def ms(v):
                    return "-" if v is None else f"{v * 1e3:.2f}"

                rows.append([arm, f, str(s["count"]), ms(s["mean"])]
                            + [ms(s[f"p{q:g}"])
                               for q in self.percentiles]
                            + [ms(s["max"])])
        widths = [max(len(r[i]) for r in [cols] + rows)
                  for i in range(len(cols))]
        fmt = "  ".join(f"{{:>{w}}}" for w in widths)
        return "\n".join(fmt.format(*r) for r in [cols] + rows)

    def __str__(self) -> str:
        return self.render()
