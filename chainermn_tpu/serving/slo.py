"""SLO reporting over serving request records.

The engine timestamps every request lifecycle (submit → admit → first
token → eviction) and :meth:`~chainermn_tpu.serving.ServingEngine.
request_records` exposes the derived per-request latencies.  This
module turns those records into the report a serving operator actually
reads: per-arm p50/p9x for queue wait, TTFT, TPOT and end-to-end
latency, on the shared :class:`~chainermn_tpu.utils.metrics.Histogram`
lattice — the same percentile math the metrics registry, the
Prometheus exposition and ``bench_serving`` use, so the number on the
dashboard IS the number in the bench JSON (small request counts ride
the histogram's exact-sample path, which is numpy-``linear``
identical; ``bench_serving`` asserts that equivalence every run).

Not every record carries every latency: a shed request
(:class:`~chainermn_tpu.serving.admission.ShedCompletion`) was never
served, and a timed-out/cancelled row may have been evicted before its
first token — their ``ttft``/``tpot``/``queue_wait`` are ``None`` or
absent.  Those values are SKIP-COUNTED per arm and field
(``summary()[arm]["skipped"]``) instead of poisoning the percentiles.

"Arms" are whatever populations are being compared: scheduling modes
(continuous vs gang, FCFS vs shed+deadline), model variants,
deployment slices.  One arm is fine too.  Under overload the metric
that separates arms is not a percentile but **goodput-under-SLO** —
tokens delivered by requests that finished within their target:
``add_arm(..., slo=...)`` scores it (a scalar e2e target or a
per-record callable) and the report grows an SLO-attainment/goodput
column; sheds and mid-stream failures count against attainment, which
is exactly why shedding hopeless work early can WIN it.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Iterable, Optional, Sequence, Union

from chainermn_tpu.utils.metrics import Histogram

__all__ = ["SLOReport"]

_FIELDS = ("queue_wait", "ttft", "tpot", "e2e")


def _field(record, name: str) -> Optional[float]:
    """A record's latency field, ``None`` when missing, ``None``, or
    unreadable (a property that raises on a partially-populated
    record must degrade to a skip, not kill the report)."""
    try:
        if isinstance(record, dict):
            return record.get(name)
        return getattr(record, name, None)
    except Exception:       # noqa: BLE001 — foreign record types
        return None


def _status(record) -> str:
    if isinstance(record, dict):
        return record.get("status", "ok")
    return getattr(record, "status", "ok")


class SLOReport:
    """Per-arm latency percentiles (and optionally SLO attainment /
    goodput) from request records.

    Args:
      percentiles: which percentiles :meth:`summary` reports
        (``p<q>`` keys; default p50/p95/p99).

    Use::

        slo = SLOReport()
        slo.add_arm("continuous", engine.request_records())
        print(slo.render())            # the operator table (ms)
        slo.summary()["continuous"]["ttft"]["p99"]   # seconds

        slo.add_arm("shed", records, slo=0.5)        # 500 ms target
        slo.summary()["shed"]["slo"]["attainment"]   # fraction met
    """

    def __init__(self, percentiles: Sequence[float] = (50, 95, 99)):
        self.percentiles = tuple(percentiles)
        self._arms: Dict[str, Dict[str, Histogram]] = {}
        self._skipped: Dict[str, Dict[str, int]] = {}
        self._slo: Dict[str, Dict[str, float]] = {}
        self._extras: Dict[str, Dict[str, float]] = {}

    def add_arm(self, name: str, records: Iterable,
                slo: Optional[Union[float, Callable]] = None,
                extras: Optional[Dict[str, float]] = None
                ) -> "SLOReport":
        """Fold ``records`` (``Completion``/``ShedCompletion``s, or
        dicts with the same field names) into arm ``name``'s
        histograms; repeated calls accumulate.  Missing/``None``
        latency fields (sheds, pre-first-token evictions) are
        skip-counted per field, never observed.

        ``slo`` turns on attainment scoring: a scalar end-to-end
        target in seconds, or ``callable(record) -> Optional[float]``
        for per-record targets (return ``None`` to exempt a record).
        A record ATTAINS its SLO iff it was fully served
        (``status == "ok"``) and its ``e2e`` is within target; the
        arm's goodput column sums the generated tokens of attaining
        records only.

        ``extras`` attaches scalar per-arm columns that are not
        latencies — speculative acceptance rate, prefix-cache hit
        rate — carried verbatim into :meth:`summary` (``"extras"``)
        and the rendered table footer; repeated calls merge keys
        (last wins).  Returns self for chaining."""
        hists = self._arms.setdefault(
            name, {f: Histogram() for f in _FIELDS})
        if extras:
            self._extras.setdefault(name, {}).update(
                {str(k): float(v) for k, v in extras.items()})
        skipped = self._skipped.setdefault(
            name, {f: 0 for f in _FIELDS})
        # the slo block only ever reflects batches scored WITH slo= —
        # folding an unscored batch's sheds into a scored arm would
        # make attainment and shed counts cover different populations
        score = self._slo.setdefault(
            name, {"scored": 0, "attained": 0, "goodput_tokens": 0,
                   "shed": 0}) if slo is not None else None
        for rec in records:
            for f in _FIELDS:
                v = _field(rec, f)
                if v is None:
                    skipped[f] += 1
                else:
                    hists[f].observe(float(v))
            if score is None:
                continue
            status = _status(rec)
            if status == "shed":
                score["shed"] += 1
            target = slo(rec) if callable(slo) else slo
            if target is None:
                continue
            score["scored"] += 1
            e2e = _field(rec, "e2e")
            if status == "ok" and e2e is not None and e2e <= target:
                score["attained"] += 1
                n = _field(rec, "n_generated")
                score["goodput_tokens"] += int(n or 0)
        return self

    @property
    def arms(self):
        return tuple(self._arms)

    def histograms(self, arm: str) -> Dict[str, Histogram]:
        """The arm's per-field lattice histograms (mergeable /
        exportable through ``utils.metrics`` like any other)."""
        return dict(self._arms[arm])

    def skipped(self, arm: str) -> Dict[str, int]:
        """Per-field count of records whose value was missing/``None``
        (shed and pre-first-token records) — reported, not observed."""
        return dict(self._skipped.get(arm, {}))

    def summary(self) -> dict:
        """``{arm: {field: {count, mean, p50, ..., max}}}``, seconds;
        plus ``"skipped"`` (per-field skip counts) and — for arms
        scored with ``slo=`` — ``"slo"``
        (``{scored, attained, attainment, goodput_tokens, shed}``)."""
        out = {}
        for arm, hists in self._arms.items():
            out[arm] = {}
            for f, h in hists.items():
                row = {"count": h.count, "mean": h.mean, "max": h.max}
                for q in self.percentiles:
                    row[f"p{q:g}"] = h.percentile(q)
                out[arm][f] = row
            out[arm]["skipped"] = self.skipped(arm)
            score = self._slo.get(arm)
            if score is not None:
                s = dict(score)
                s["attainment"] = (s["attained"] / s["scored"]
                                   if s["scored"] else None)
                out[arm]["slo"] = s
            extras = self._extras.get(arm)
            if extras:
                out[arm]["extras"] = dict(extras)
        return out

    def to_dict(self) -> dict:
        return {"percentiles": list(self.percentiles),
                "arms": self.summary()}

    def write_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=float)
        return path

    def render(self) -> str:
        """The printable table, milliseconds (TPOT included — it is a
        latency too, just per token); skip counts per metric, and an
        SLO attainment/goodput line per scored arm."""
        cols = ["arm", "metric", "n", "skip", "mean_ms"] + \
            [f"p{q:g}_ms" for q in self.percentiles] + ["max_ms"]
        rows = []
        summary = self.summary()
        for arm, fields in summary.items():
            for f in _FIELDS:
                s = fields[f]

                def ms(v):
                    return "-" if v is None else f"{v * 1e3:.2f}"

                rows.append([arm, f, str(s["count"]),
                             str(fields["skipped"].get(f, 0)),
                             ms(s["mean"])]
                            + [ms(s[f"p{q:g}"])
                               for q in self.percentiles]
                            + [ms(s["max"])])
        widths = [max(len(r[i]) for r in [cols] + rows)
                  for i in range(len(cols))]
        fmt = "  ".join(f"{{:>{w}}}" for w in widths)
        lines = [fmt.format(*r) for r in [cols] + rows]
        for arm, fields in summary.items():
            score = fields.get("slo")
            if score is not None:
                att = score["attainment"]
                lines.append(
                    f"{arm}  slo: {score['attained']}/{score['scored']}"
                    f" attained"
                    + (f" ({att * 100:.1f}%)" if att is not None
                       else "")
                    + f"  goodput {score['goodput_tokens']} tok"
                    + f"  shed {score['shed']}")
            extras = fields.get("extras")
            if extras:
                lines.append(f"{arm}  " + "  ".join(
                    f"{k} {v:.4g}" for k, v in sorted(extras.items())))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.render()
