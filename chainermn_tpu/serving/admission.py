"""Admission control — the serving engine's overload survival layer.

The PR 8 engine accepts every request forever: the queue grows without
bound, a request whose deadline is already hopeless ages in it anyway,
and one tenant can starve every other.  Under overload (λ > capacity —
the normal state of a popular service) that is the difference between
a demo and a service: goodput collapses because capacity is spent on
requests nobody is still waiting for.  This module closes the loop the
ROADMAP names, using the measurements PR 9 already collects:

- :class:`ServiceTimePredictor` — service-time prediction for free
  from the same ``serve/ttft`` / ``serve/tpot`` lattice histograms the
  metrics registry exposes (:mod:`chainermn_tpu.utils.metrics`): the
  predicted end-to-end time of a ``max_new``-token request is a
  configurable percentile of observed TTFT plus ``max_new - 1`` times
  the TPOT percentile.  Cold (no observations, no defaults) it
  predicts nothing and admission is optimistic — shedding needs
  evidence.  The engine additionally feeds an admit→first-token
  stream (``observe_service_ttft``) so deadline decisions can SPLIT
  the prediction: live-queue drain for the wait term, queue-free
  service time for the rest — observed submit→first-token TTFT folds
  each sample's own queue wait in, and a prediction built on it
  over-sheds exactly when the queue is emptier than the history it
  was measured under.
- :class:`AdmissionController` — the submit/admit-time decisions:
  a bounded queue with priority displacement (a more important
  arrival may displace the least important queued request instead of
  being rejected), per-tenant in-flight token quotas, and fast-reject
  load shedding of requests whose predicted completion would breach
  their deadline.  Decisions are returned as data, never raised —
  overload is normal operation, not an error.
- :class:`ShedCompletion` — the typed reject record: reason-coded
  (:data:`SHED_REASONS`), carried in ``request_records()`` next to
  real completions, counted in ``serve/shed_<reason>`` metrics, and
  handled by :class:`~chainermn_tpu.serving.slo.SLOReport` (shed
  records have no latency fields; the report skip-counts them instead
  of poisoning percentiles).

The engine half (deadline/timeout enforcement, ``cancel()``, the
``"deadline"`` scheduling policy, decode-round quarantine) lives in
:mod:`~chainermn_tpu.serving.engine`; this module is pure host-side
policy with no jax dependency, unit-testable without a mesh.  See
docs/SERVING.md "Overload and admission".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from chainermn_tpu.utils.metrics import Histogram

__all__ = ["AdmissionController", "SHED_REASONS", "ServiceTimePredictor",
           "ShedCompletion"]

#: Every reason code a :class:`ShedCompletion` may carry.  Each is
#: counted in the ``serve/shed_<reason>`` counter when the metrics
#: registry is enabled (plus ``serve/shed_total``).
SHED_REASONS = (
    "queue_full",     # bounded queue at capacity (backpressure), or
                      # displaced from it by a higher-priority arrival
    "over_quota",     # tenant's in-flight token quota exhausted
    "deadline",       # predicted completion would breach the deadline
    "timeout",        # deadline expired while still queued
    "cancelled",      # caller cancel() before admission
    "quarantined",    # staging/prefill failed for THIS request
    "draining",       # engine is draining for an epoch change (resize)
    "stale_epoch",    # submit carried an epoch the engine has moved past
    "overload",       # protective shed: an SLO burn-rate alert is
                      # firing and the request's class is below the
                      # protected tier (utils/alerts.py advisory)
)


@dataclasses.dataclass(eq=False)     # identity equality, like Completion
class ShedCompletion:
    """A request that terminated WITHOUT being served: rejected at
    submit, shed from the queue, or cancelled before admission.

    Flows through the same channels as a real
    :class:`~chainermn_tpu.serving.engine.Completion` (``submit``
    return / ``step()`` output / ``request_records()``) so callers
    handle one stream of terminal records.  It has NO latency fields —
    nothing was served — which is exactly what
    :meth:`SLOReport.add_arm <chainermn_tpu.serving.slo.SLOReport.
    add_arm>` skip-counts.
    """

    rid: str
    prompt: np.ndarray
    reason: str                  # one of SHED_REASONS
    t_submit: float
    t_shed: float
    max_new: int = 0
    priority: int = 0
    tenant: Optional[str] = None
    detail: str = ""
    # Predicted seconds until the condition that caused this shed
    # clears (the retry-after header a front-end should quote).
    # Populated for CAPACITY sheds — queue_full and drain-mode from
    # the predictor's queue-drain estimate, over_quota from the
    # TENANT's predicted in-flight drain — ``None`` while the
    # predictor is cold, and for reasons where retrying is pointless
    # (deadline, stale_epoch).
    retry_after: Optional[float] = None
    # The request's causal-trace identity (engine-generated or caller-
    # propagated) — resolves against the engine's RequestTraceStore,
    # where shed traces are ALWAYS retained.
    trace_id: Optional[str] = None

    status = "shed"              # class attr: never "ok"

    def __post_init__(self):
        if self.reason not in SHED_REASONS:
            raise ValueError(
                f"reason {self.reason!r} not in {SHED_REASONS}")

    @property
    def tokens(self) -> np.ndarray:
        return np.zeros((0,), np.int32)

    @property
    def n_generated(self) -> int:
        return 0


class ServiceTimePredictor:
    """Predicted service time from the live TTFT/TPOT distributions.

    Runs on the SAME fixed log-lattice histograms as the ``serve/ttft``
    / ``serve/tpot`` registry metrics (the PR 9 design point: the
    buckets the dashboard reads are the buckets the predictor reads),
    fed by the engine at the same timestamp-holding points.  The
    prediction is deliberately a tail percentile, not the mean — an
    admission decision that must hold under load should quote the
    latency a request is LIKELY TO SEE, and under overload the tail is
    where requests live.

    Args:
      quantile: which percentile of the observed distributions to
        predict with (default 75 — pessimistic enough to shed early
        under load, not so pessimistic that transient spikes shed
        everything).
      default_ttft / default_tpot: cold-start estimates used until the
        histograms hold at least ``min_count`` observations.  ``None``
        (the default) means a cold predictor predicts nothing
        (:meth:`predict_e2e` returns ``None``) and admission stays
        optimistic — shedding needs evidence.
      min_count: observations required per histogram before the live
        percentile replaces the default.
    """

    def __init__(self, quantile: float = 75.0,
                 default_ttft: Optional[float] = None,
                 default_tpot: Optional[float] = None,
                 min_count: int = 8):
        if not 0 < quantile <= 100:
            raise ValueError(f"quantile={quantile} not in (0, 100]")
        if min_count < 1:
            raise ValueError(f"min_count={min_count} must be >= 1")
        self.quantile = float(quantile)
        self.default_ttft = default_ttft
        self.default_tpot = default_tpot
        self.min_count = int(min_count)
        self.ttft_hist = Histogram()
        self.tpot_hist = Histogram()
        # admit→first-token (queue-wait EXCLUDED): the service-side
        # half of the split prediction — see :meth:`service_ttft`
        self.service_hist = Histogram()
        # percentile over up to 512 exact samples is a sort; the
        # scheduler asks per queued request per tick, so memoize until
        # the next observation
        self._cache: dict = {}

    # -- feeding (the engine calls these where it observes serve/*) --- #

    def observe_ttft(self, seconds: float) -> None:
        self.ttft_hist.observe(seconds)
        self._cache.pop("ttft", None)

    def observe_service_ttft(self, seconds: float) -> None:
        """Feed an ADMIT→first-token measurement — the queue-free
        service time.  ``serve/ttft`` (submit→first-token) folds the
        request's own queue wait into the sample, so a predictor fed
        only that double-counts waiting when it also models the queue;
        this stream is the clean service half."""
        self.service_hist.observe(seconds)
        self._cache.pop("service", None)

    def observe_tpot(self, seconds: float) -> None:
        self.tpot_hist.observe(seconds)
        self._cache.pop("tpot", None)

    # -- predictions -------------------------------------------------- #

    def _estimate(self, key: str, hist: Histogram,
                  default: Optional[float]) -> Optional[float]:
        if key not in self._cache:
            self._cache[key] = (hist.percentile(self.quantile)
                                if hist.count >= self.min_count
                                else default)
        return self._cache[key]

    def ttft(self) -> Optional[float]:
        """Predicted submit→first-token time under current load."""
        return self._estimate("ttft", self.ttft_hist, self.default_ttft)

    def service_ttft(self) -> Optional[float]:
        """Predicted ADMIT→first-token time — service only, no queue
        wait.  ``None`` until :meth:`observe_service_ttft` has fed at
        least ``min_count`` samples (no default: the split model needs
        real service evidence, else callers fall back to the blended
        :meth:`predict_e2e`)."""
        return self._estimate("service", self.service_hist, None)

    def tpot(self) -> Optional[float]:
        """Predicted steady-state seconds per generated token."""
        return self._estimate("tpot", self.tpot_hist, self.default_tpot)

    def predict_e2e(self, max_new: int) -> Optional[float]:
        """Predicted submit→done seconds for a fresh ``max_new``-token
        request (TTFT + (max_new−1)·TPOT); ``None`` while cold.

        Caveat the split model exists to fix: the observed TTFT folds
        each SAMPLE's queue wait in, so this estimate is conditioned
        on the historical queue, not the live one — with an empty
        queue it over-predicts (and over-sheds).  Deadline decisions
        prefer :meth:`predict_service` plus an explicit
        :meth:`predict_queue_drain` wait term when service evidence
        exists."""
        t, p = self.ttft(), self.tpot()
        if t is None or p is None:
            return None
        return t + p * max(int(max_new) - 1, 0)

    def predict_service(self, max_new: int) -> Optional[float]:
        """Predicted ADMIT→done seconds for a ``max_new``-token
        request — pure service time (``service_ttft`` + (max_new−1)·
        TPOT), no queue-wait term; ``None`` without live service
        evidence."""
        s, p = self.service_ttft(), self.tpot()
        if s is None or p is None:
            return None
        return s + p * max(int(max_new) - 1, 0)

    def predict_remaining(self, tokens_left: int) -> Optional[float]:
        """Predicted seconds to generate ``tokens_left`` more tokens
        for a request already at the head of service (no queue-wait
        term — that has either elapsed or is the scheduler's to
        weigh); ``None`` while cold."""
        p = self.tpot()
        if p is None:
            return None
        return p * max(int(tokens_left), 0)

    def predict_queue_drain(self, backlog_tokens: int,
                            n_slots: int) -> Optional[float]:
        """Predicted seconds until a backlog of ``backlog_tokens``
        budget tokens (queued ``max_new`` plus active rows' remaining
        budgets) drains across ``n_slots`` decode lanes — the
        retry-after estimate a capacity shed quotes
        (ROADMAP admission open end #3).  The aggregate token
        throughput model (``n_slots / TPOT``) deliberately ignores
        per-request TTFT: across a backlog, prefill cost is amortised
        and the steady-state decode rate dominates.  ``None`` while
        cold — a retry header should never be invented without
        evidence."""
        p = self.tpot()
        if p is None:
            return None
        return p * max(int(backlog_tokens), 0) / max(int(n_slots), 1)

    def snapshot(self) -> dict:
        return {
            "quantile": self.quantile,
            "ttft": self.ttft(),
            "service_ttft": self.service_ttft(),
            "tpot": self.tpot(),
            "ttft_count": self.ttft_hist.count,
            "service_count": self.service_hist.count,
            "tpot_count": self.tpot_hist.count,
        }


class AdmissionController:
    """Submit/admit-time policy: bounded queue with priority
    displacement, per-tenant in-flight token quotas, and predictive
    deadline shedding.

    Attach to an engine via ``ServingEngine(..., admission=ctrl)`` (or
    assign ``engine.admission`` between arms — host-side only, no
    recompile).  Priorities are SMALLER-IS-MORE-IMPORTANT integers
    (class 0 outranks class 1); requests default to class 0.

    Args:
      max_queue: queue bound.  A submit that would exceed it is shed
        ``"queue_full"`` — unless some queued request has a strictly
        LOWER priority (numerically greater), in which case the least
        important, newest such request is displaced instead and the
        arrival admitted (the priority-class contract: class 0 traffic
        is never locked out by a backlog of class 2).  ``None`` (the
        default) = unbounded, the pre-admission behaviour.
      quotas: per-tenant in-flight token budgets — the sum of
        ``max_new`` over a tenant's queued + active requests may not
        exceed its quota; a submit that would is shed ``"over_quota"``.
        Tenants absent from the dict fall back to ``default_quota``
        (``None`` = unlimited).  ``Request.tenant=None`` rows form
        their own anonymous tenant.
      default_quota: quota for tenants not named in ``quotas``.
      predictor: the :class:`ServiceTimePredictor` deadline decisions
        consult (one is created if omitted).  The engine feeds it
        live; prime it (``observe_*`` or ``default_*``) to shed from
        the first request.
      shed_on_deadline: predictive shedding switch — at submit, a
        request whose predicted e2e already breaches its deadline is
        shed ``"deadline"``; while queued, one whose remaining
        prediction breaches it is shed at the next admit scan rather
        than aging further.  Expired deadlines (``"timeout"``) are
        enforced by the engine regardless.
      alert_advisor: the PROTECTIVE-shedding hook closing the alerting
        loop (docs/OBSERVABILITY.md "Burn-rate alerts"): an object
        with ``.protective()`` (an
        :class:`~chainermn_tpu.utils.alerts.AlertManager`) or any
        callable returning truthy while protection should be on.
        While it is, arriving requests whose priority class is
        NUMERICALLY GREATER than ``protect_priority`` (less important)
        are shed ``"overload"`` at submit — the error budget is
        burning, so below-tier traffic is turned away before it makes
        the tail worse.  Advisory only: a raising/broken advisor
        degrades to "not protective", never to a crash.
      protect_priority: the most-important class still SHELTERED from
        protective shedding (default 0: class 0 is never overload-shed,
        everything else is while an alert fires).
    """

    def __init__(self, *, max_queue: Optional[int] = None,
                 quotas: Optional[Dict[Optional[str], float]] = None,
                 default_quota: Optional[float] = None,
                 predictor: Optional[ServiceTimePredictor] = None,
                 shed_on_deadline: bool = True,
                 alert_advisor=None, protect_priority: int = 0,
                 overload_retry_after: Optional[float] = None,
                 tenant_weights: Optional[Dict[Optional[str],
                                               float]] = None,
                 default_weight: float = 1.0,
                 wfq_quantum: Optional[float] = None):
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")
        for t, w in (tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(
                    f"weight for tenant {t!r} must be > 0, got {w}")
        if default_weight <= 0:
            raise ValueError(
                f"default_weight={default_weight} must be > 0")
        if wfq_quantum is not None and wfq_quantum <= 0:
            raise ValueError(
                f"wfq_quantum={wfq_quantum} must be > 0")
        if overload_retry_after is not None \
                and overload_retry_after <= 0:
            raise ValueError(
                f"overload_retry_after={overload_retry_after} "
                "must be > 0 seconds")
        for t, q in (quotas or {}).items():
            if q is not None and q < 1:
                raise ValueError(
                    f"quota for tenant {t!r} must be >= 1, got {q}")
        if default_quota is not None and default_quota < 1:
            raise ValueError(
                f"default_quota={default_quota} must be >= 1")
        self.max_queue = max_queue
        self.quotas = dict(quotas or {})
        self.default_quota = default_quota
        self.predictor = predictor or ServiceTimePredictor()
        self.shed_on_deadline = shed_on_deadline
        self.alert_advisor = alert_advisor
        self.protect_priority = int(protect_priority)
        #: the come-back hint an ``"overload"`` shed carries.  The
        #: queue-drain predictor is the WRONG signal here — protective
        #: shedding resolves with the burn-rate alert's short window,
        #: not the backlog (an empty queue would hint ~0 and invite a
        #: retry storm mid-protection) — so this is an operator knob,
        #: e.g. the protect rules' short-window length; ``None`` = no
        #: hint (clients apply their own backoff).
        self.overload_retry_after = overload_retry_after
        self.tenant_weights = dict(tenant_weights or {})
        self.default_weight = float(default_weight)
        self.wfq_quantum = wfq_quantum
        # deficit-round-robin state (the engine's "wfq" policy):
        # per-tenant token credit, whose turn the rotation is on, and
        # whether that turn's quantum was already granted
        self._wfq_deficit: Dict[Optional[str], float] = {}
        self._wfq_turn: Optional[str] = None
        self._wfq_in_turn: Dict[Optional[str], bool] = {}

    def quota_for(self, tenant: Optional[str]) -> Optional[float]:
        return self.quotas.get(tenant, self.default_quota)

    def weight_for(self, tenant: Optional[str]) -> float:
        return self.tenant_weights.get(tenant, self.default_weight)

    def wfq_pick(self, queue: Sequence):
        """Deficit-round-robin tenant scheduling (the engine's
        ``policy="wfq"``): within the most important priority class
        present, tenants take turns accruing token credit
        (``quantum × weight`` per lap of the rotation) and a tenant's
        head-of-line request admits once its credit covers the
        request's ``max_new`` cost — so a tenant with weight 2 is
        served about twice the TOKENS of a weight-1 tenant, a flood
        from one tenant cannot starve another (every lap credits
        everyone — starvation-freedom is structural), and within a
        tenant order stays FCFS.

        Quotas bound how much of a tenant can be IN FLIGHT; WFQ
        decides who goes NEXT — the scheduling half the ROADMAP's
        admission item called out as missing.  The quantum defaults
        to the largest head-of-line cost so every lap can serve at
        least one request (no busy idling); state (deficits, whose
        turn) persists across picks and resets only for tenants with
        NOTHING queued in any class, the classic DRR contract.
        Deterministic: ties break by the rotation, which follows
        first-arrival order.

        The pick does NOT debit the winner's credit — an admission
        can still fail downstream (pool full, horizon full) with the
        request left queued, and charging per attempt would skew the
        weighted shares.  The engine settles the cost at SUCCESSFUL
        admission via :meth:`wfq_charge`; a retried pick meanwhile
        re-selects the same tenant (its credit still covers the same
        head) without granting fresh quanta."""
        if not queue:
            raise ValueError("wfq_pick on an empty queue")
        cls = min(r.priority for r in queue)
        queued_tenants = {r.tenant for r in queue}
        heads: Dict[Optional[str], object] = {}
        for r in queue:
            if r.priority == cls and r.tenant not in heads:
                heads[r.tenant] = r
        ring = list(heads)
        # classic DRR: a flow that EMPTIES loses its deficit — judged
        # against the whole queue, not this class's heads, so a
        # transient high-priority arrival cannot zero waiting
        # lower-class tenants' accrued credit
        self._wfq_deficit = {t: d for t, d in self._wfq_deficit.items()
                             if t in queued_tenants}
        self._wfq_in_turn = {t: v for t, v in self._wfq_in_turn.items()
                             if t in queued_tenants}
        quantum = self.wfq_quantum or max(
            float(h.max_new) for h in heads.values())
        idx = ring.index(self._wfq_turn) if self._wfq_turn in ring \
            else 0
        min_w = min(self.weight_for(t) for t in ring)
        max_cost = max(float(h.max_new) for h in heads.values())
        laps = int(max_cost / max(quantum * min_w, 1e-9)) + 2
        for _ in range(laps * len(ring) + 1):
            t = ring[idx]
            if not self._wfq_in_turn.get(t, False):
                self._wfq_deficit[t] = (self._wfq_deficit.get(t, 0.0)
                                        + quantum * self.weight_for(t))
                self._wfq_in_turn[t] = True
            head = heads[t]
            if self._wfq_deficit[t] >= head.max_new:
                self._wfq_turn = t
                return head
            self._wfq_in_turn[t] = False
            idx = (idx + 1) % len(ring)
        return heads[ring[0]]      # unreachable: laps bound the credit

    def wfq_charge(self, req) -> None:
        """Settle a served pick's cost against its tenant's DRR
        credit — called by the engine at SUCCESSFUL admission (the
        pick itself never debits; see :meth:`wfq_pick`).  No-op for
        tenants without DRR state (non-WFQ policies admit through the
        same path)."""
        if req.tenant in self._wfq_deficit:
            self._wfq_deficit[req.tenant] -= float(req.max_new)

    def protective(self) -> bool:
        """Whether the alert advisory currently calls for protective
        shedding (False without an advisor, and on ANY advisor
        failure — advice must never become an outage)."""
        adv = self.alert_advisor
        if adv is None:
            return False
        try:
            fn = getattr(adv, "protective", adv)
            return bool(fn())
        except Exception:       # noqa: BLE001 — advisory only
            return False

    def check_submit(self, req, queue: Sequence,
                     inflight: Dict[Optional[str], int],
                     n_slots: Optional[int] = None,
                     ahead_tokens: Optional[int] = None
                     ) -> Tuple[bool, Optional[str], Optional[object]]:
        """The submit-time verdict: ``(admit, reason, victim)``.

        - ``(True, None, None)`` — admit to the queue.
        - ``(False, reason, None)`` — shed the ARRIVAL with
          ``reason``.
        - ``(True, "queue_full", victim)`` — admit the arrival, but
          displace ``victim`` (a queued request) to make room; the
          engine sheds the victim ``"queue_full"``.

        Check order: protective overload advisory (fleet health beats
        any one request), quota (per-tenant fairness), predicted
        deadline (no point queueing the hopeless), then the queue
        bound.

        ``n_slots`` (the engine passes its lane count) enables the
        SPLIT deadline prediction: the wait term is the LIVE queue's
        drain estimate conditioned on this request's actual queue
        position, the service term is the queue-free
        :meth:`ServiceTimePredictor.predict_service`.  Without it (or
        without service evidence) the blended :meth:`predict_e2e`
        estimate is used — which folds HISTORICAL queue waits into a
        prediction for THIS queue, the over-shedding flaw the split
        fixes (an empty queue inherits the congested past's wait).

        ``ahead_tokens`` (the engine passes its scheduling policy's
        verdict) narrows the wait term further, to only the queued
        budget the policy would serve BEFORE this request — without
        it the whole queue is charged, which over-sheds under any
        policy that can serve the new arrival early (deadline slack,
        short prompt, priority).
        """
        if req.priority > self.protect_priority and self.protective():
            return False, "overload", None
        quota = self.quota_for(req.tenant)
        if quota is not None and \
                inflight.get(req.tenant, 0) + req.max_new > quota:
            return False, "over_quota", None
        if self.shed_on_deadline and req.deadline is not None:
            pred = self._predict_wait_and_service(req.max_new, queue,
                                                  n_slots,
                                                  ahead_tokens)
            if pred is not None and req.t_submit + pred > req.deadline:
                return False, "deadline", None
        if self.max_queue is not None and len(queue) >= self.max_queue:
            victim = self._displacement_victim(req, queue)
            if victim is not None:
                return True, "queue_full", victim
            return False, "queue_full", None
        return True, None, None

    def _predict_wait_and_service(self, max_new: int, queue: Sequence,
                                  n_slots: Optional[int],
                                  ahead_tokens: Optional[int] = None
                                  ) -> Optional[float]:
        """Queue-position-conditioned e2e prediction: the LIVE queued
        backlog's drain time (zero for an empty queue) plus the pure
        service time.  The backlog is ``ahead_tokens`` when the caller
        supplies the policy-conditioned queue position (only requests
        served BEFORE this one count), else the whole queue — the
        conservative charge.  Falls back to the blended
        :meth:`predict_e2e` when the split inputs are missing."""
        service = self.predictor.predict_service(max_new)
        if service is None or n_slots is None:
            return self.predictor.predict_e2e(max_new)
        wait = 0.0
        backlog = ahead_tokens if ahead_tokens is not None \
            else (sum(int(r.max_new) for r in queue) if queue else 0)
        if backlog:
            drain = self.predictor.predict_queue_drain(backlog,
                                                       n_slots)
            if drain is not None:
                wait = drain
        return wait + service

    @staticmethod
    def _displacement_victim(req, queue: Sequence):
        """The least important, NEWEST queued request with strictly
        lower priority than ``req`` (newest = least sunk queue-wait);
        ``None`` when nobody outranks nobody.  Deterministic: ties on
        priority break by submit order."""
        worst_i, worst = max(
            enumerate(queue), key=lambda t: (t[1].priority, t[0]))
        del worst_i
        if worst.priority > req.priority:
            return worst
        return None

    def retry_after(self, backlog_tokens: int,
                    n_slots: int) -> Optional[float]:
        """The retry-after value a capacity shed should carry: the
        predictor's queue-drain estimate for the live backlog
        (``None`` while cold).  The engine computes the backlog —
        queued ``max_new`` plus active rows' remaining budgets — at
        the moment of the shed."""
        return self.predictor.predict_queue_drain(backlog_tokens,
                                                  n_slots)

    def check_queued(self, req, now: float) -> Optional[str]:
        """Admit-scan verdict for a QUEUED request: ``"deadline"`` when
        its remaining prediction can no longer meet its deadline,
        else ``None`` (keep waiting).  Expired deadlines are the
        engine's own ``"timeout"`` check, run before this one."""
        if not self.shed_on_deadline or req.deadline is None:
            return None
        rem = self.predictor.predict_remaining(req.max_new)
        if rem is not None and now + rem > req.deadline:
            return "deadline"
        return None
