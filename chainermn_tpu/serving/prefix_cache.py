"""Copy-on-write prefix sharing over the block-paged KV pool.

N live requests that share a system prompt each pay full prefill and
full pool pressure under the PR 8 staging layer — every staged row
owns a private copy of KV the pool already holds N-1 times.  This
module is the sharing layer the ROADMAP names: REFCOUNTED physical
blocks plus a prefix trie keyed by token-id chunks, so requests whose
prompts share a prefix hold ONE physical copy of the shared blocks and
admission stages (prefills and allocates) only the divergent suffix.

Design, in the terms the engine uses:

- **Left-aligned block identity.**  A staged prompt's token ``i``
  lives in block ``i // block`` at intra-block position ``i % block``
  (the engine left-aligns staging prefills for exactly this reason).
  K/V of token ``i`` is a pure function of ``tokens[:i+1]`` — position
  embeddings index the token's own index, attention sees only earlier
  prompt tokens — so a FULL block's content is content-addressed by
  the token prefix through its end.  That prefix is the trie key
  (:class:`PrefixTrie` realizes the chunked-token trie as a hash chain
  over ``tokens[: (j+1) * block]``).
- **Partial blocks never share.**  The last block of a prompt whose
  length is not a block multiple holds garbage K/V past the prompt's
  end; it stays private to its row.  Divergence INSIDE a block
  therefore never aliases: the divergent suffix always forks onto
  fresh blocks at stage time — copy-on-write at block granularity,
  with :meth:`RefcountedBlockPool.fork_for_write` as the explicit
  fork primitive guarding any write aimed at a block with other
  holders.
- **Refcounts, not ownership.**  A block's holders are the rows whose
  tables contain it plus (at most once) the trie.  ``free_row`` only
  decrements; a block returns to the free list when its last holder
  lets go — so evicting or stealing a staged row never invalidates
  the blocks other rows share with it, and a completed request's full
  blocks REMAIN cached for the next arrival (that is the cache).
  Under pool pressure :meth:`reclaim` drops least-recently-used
  trie-only blocks; blocks any row still holds refuse eviction.

The device arrays live with the engine (``kv_blocks`` pool ops); this
module is host-side bookkeeping only, unit-testable without jax.  See
docs/SERVING.md "Prefix sharing".
"""

from __future__ import annotations

import collections
import dataclasses
import json
import zlib
from typing import Dict, List, Optional

import numpy as np

__all__ = ["PrefixTrie", "RefcountedBlockPool", "StagePlan",
           "prefix_snapshot", "load_prefix_snapshot",
           "PREFIX_SNAPSHOT_VERSION"]


def _prefix_key(tokens: np.ndarray, end: int) -> bytes:
    """The content address of the full block ending at token ``end``:
    the whole token prefix through it (K/V inside the block depends on
    every earlier token, so nothing shorter is sound)."""
    return np.ascontiguousarray(tokens[:end], np.int32).tobytes()


class PrefixTrie:
    """Chunked-token prefix trie, realized as an LRU hash chain:
    ``tokens[: (j+1) * block] -> block_id`` for every cached FULL
    block.  A lookup walks leading full blocks until the first miss —
    exactly the trie descent, one hash per chunk."""

    def __init__(self, block: int):
        self.block = int(block)
        self._map: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._key_of: Dict[int, bytes] = {}
        # sub-block divergence support: each cached block remembers its
        # own token slice, and each parent prefix remembers ONE cached
        # child block (first writer wins, like insert) so stage() can
        # measure how far into the next block a new prompt agrees with
        # cached content before diverging
        self._tokens_of: Dict[int, np.ndarray] = {}
        self._child_of: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._parent_of: Dict[int, bytes] = {}

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, block_id: int) -> bool:
        return block_id in self._key_of

    def lookup_run(self, tokens: np.ndarray) -> List[int]:
        """Block ids of the LONGEST cached leading run of full blocks
        of ``tokens`` (possibly empty); hits are LRU-refreshed."""
        run: List[int] = []
        for j in range(len(tokens) // self.block):
            key = _prefix_key(tokens, (j + 1) * self.block)
            bid = self._map.get(key)
            if bid is None:
                break
            self._map.move_to_end(key)
            run.append(bid)
        return run

    def insert(self, tokens: np.ndarray, j: int, block_id: int) -> bool:
        """Cache full block ``j`` of ``tokens`` as ``block_id``; False
        when that prefix is already cached (first writer wins — the
        content is identical by construction)."""
        key = _prefix_key(tokens, (j + 1) * self.block)
        if key in self._map:
            return False
        self._map[key] = block_id
        self._key_of[block_id] = key
        self._tokens_of[block_id] = np.ascontiguousarray(
            tokens[j * self.block:(j + 1) * self.block], np.int32).copy()
        parent = _prefix_key(tokens, j * self.block)
        if parent not in self._child_of:
            self._child_of[parent] = block_id
            self._parent_of[block_id] = parent
        return True

    def peek_child(self, tokens: np.ndarray, n_matched: int):
        """A cached FULL block extending ``tokens``' first
        ``n_matched`` blocks, as ``(block_id, its token slice)`` —
        ``None`` when no child is cached.  The sub-block fork probe:
        the caller diffs the slice against its own next block to find
        how many leading K/V positions a device copy can reuse."""
        parent = _prefix_key(tokens, n_matched * self.block)
        bid = self._child_of.get(parent)
        if bid is None:
            return None
        self._map.move_to_end(self._key_of[bid])
        return bid, self._tokens_of[bid]

    def drop_block(self, block_id: int) -> bool:
        key = self._key_of.pop(block_id, None)
        if key is None:
            return False
        del self._map[key]
        self._tokens_of.pop(block_id, None)
        parent = self._parent_of.pop(block_id, None)
        if parent is not None:
            del self._child_of[parent]
        return True

    def lru_blocks(self):
        """Cached block ids, least recently used first."""
        return list(self._map.values())


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """One staging decision: ``table`` is the row's physical blocks in
    token order; the first ``n_shared`` came from the trie (their
    prefill is skipped), the last ``n_new`` were freshly allocated and
    must be prefilled + scattered.  ``n_shared > 0 and n_new > 0`` is
    the copy-on-write FORK: the row's chain leaves the shared prefix
    for private blocks at token ``n_shared * block``.

    ``copy_src``/``n_copied`` refine the fork to SUB-block
    granularity: when a cached child block agrees with the prompt on
    its first ``n_copied`` tokens, the first new block is
    device-copied from ``copy_src`` (the caller owes the
    ``copy_block`` dispatch, then :meth:`RefcountedBlockPool.
    copy_done` to drop the transient reference ``stage`` holds on the
    source) and prefill resumes at token ``n_shared * block +
    n_copied`` instead of re-deriving the whole block."""

    table: List[int]
    n_shared: int
    n_new: int
    copy_src: Optional[int] = None
    n_copied: int = 0

    def __post_init__(self):
        assert self.n_shared + self.n_new == len(self.table)
        assert (self.copy_src is None) == (self.n_copied == 0)


class RefcountedBlockPool:
    """Refcounted free-list allocator with prefix-trie block sharing.

    Drop-in for the engine half of
    :class:`~chainermn_tpu.serving.kv_blocks.BlockAllocator` (same
    ``free_row`` / ``padded_table`` / ``n_free`` / ``utilization``
    surface) plus the sharing API: :meth:`stage` plans a row's blocks
    against the trie, :meth:`insert_cached` publishes its full blocks
    after prefill, :meth:`reclaim` drops LRU cache-only blocks under
    pressure, :meth:`fork_for_write` is the copy-on-write escape
    hatch, and :meth:`leak_report` audits the refcount invariants
    (the suite-wide pool-leak fixture runs it after every serving
    test).

    ``share=False`` disables the trie entirely: every block then has
    exactly one holder and the pool degenerates to the PR 8
    allocator's behaviour.
    """

    def __init__(self, n_blocks: int, block: int, *, share: bool = True):
        if n_blocks < 1 or block < 1:
            raise ValueError(
                f"n_blocks={n_blocks} and block={block} must be >= 1")
        self.n_blocks = int(n_blocks)
        self.block = int(block)
        self.share = bool(share)
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._refs: Dict[int, int] = {}
        self._tables: Dict[object, List[int]] = {}
        self._trie = PrefixTrie(block)
        self.n_hits = 0             # blocks served from the trie
        self.n_prefilled = 0        # blocks that needed prefill
        self.n_forks = 0            # fork_for_write invocations that forked
        self.n_partial_copies = 0   # sub-block forks (copy_src plans)
        self.n_reclaimed = 0        # cache blocks dropped under pressure
        self.peak_blocks_used = 0   # physical residency (rows + cache)
        self.peak_row_blocks = 0    # unreclaimable pressure (row-held)

    # -- accounting ---------------------------------------------------- #

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_cached(self) -> int:
        return len(self._trie)

    @property
    def n_shared_blocks(self) -> int:
        """Blocks currently held by more than one holder — the
        physical copies prefix sharing is saving."""
        return sum(1 for r in self._refs.values() if r > 1)

    @property
    def utilization(self) -> float:
        """Fraction of pool blocks held by ROWS (cache-only blocks are
        reclaimable on demand, so they don't count as pressure)."""
        row_held = set()
        for ids in self._tables.values():
            row_held.update(ids)
        return len(row_held) / self.n_blocks

    def rows(self):
        return list(self._tables)

    def table(self, row_id) -> List[int]:
        return list(self._tables[row_id])

    def refcount(self, block_id: int) -> int:
        return self._refs.get(block_id, 0)

    # -- allocation ---------------------------------------------------- #

    def _take(self, n: int) -> Optional[List[int]]:
        shortfall = n - len(self._free)
        if shortfall > 0 and self.reclaim(shortfall) < shortfall:
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            assert b not in self._refs      # double-alloc guard
            self._refs[b] = 1
        return ids

    def _note_peak(self):
        self.peak_blocks_used = max(self.peak_blocks_used,
                                    self.n_blocks - len(self._free))
        held = set()
        for ids in self._tables.values():
            held.update(ids)
        self.peak_row_blocks = max(self.peak_row_blocks, len(held))

    def alloc(self, row_id, n: int) -> Optional[List[int]]:
        """Share-oblivious allocation (the ``BlockAllocator``
        contract): ``n`` fresh private blocks or ``None``, taking
        nothing on failure."""
        if row_id in self._tables:
            raise ValueError(f"row {row_id!r} already holds blocks")
        if n < 0:
            raise ValueError(f"n={n} must be >= 0")
        ids = self._take(n)
        if ids is None:
            return None
        self._tables[row_id] = ids
        self._note_peak()
        return list(ids)

    def stage(self, row_id, tokens) -> Optional[StagePlan]:
        """Plan ``row_id``'s staging against the trie: reuse the
        longest cached run of leading full blocks (refcount++), then
        allocate the divergent suffix — ``ceil(P/block) - n_shared``
        fresh blocks.  All-or-nothing like :meth:`alloc`: on an
        unsatisfiable suffix nothing is taken and ``None`` returns
        (the caller steals or backpressures)."""
        if row_id in self._tables:
            raise ValueError(f"row {row_id!r} already holds blocks")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n_real = -(-len(tokens) // self.block)
        run = self._trie.lookup_run(tokens) if self.share else []
        # reference the hits BEFORE allocating: the suffix allocation
        # may reclaim cache-only blocks, and an unreferenced hit is
        # exactly that
        for b in run:
            self._refs[b] += 1
        # sub-block fork probe: a cached child block whose leading
        # tokens agree with ours lets the first divergent block start
        # as a device copy.  The source holds a TRANSIENT reference
        # (same reclaim hazard as the run hits) until copy_done().
        copy_src, n_copied = None, 0
        if self.share and n_real > len(run):
            ours = tokens[len(run) * self.block:
                          (len(run) + 1) * self.block]
            child = self._trie.peek_child(tokens, len(run))
            if child is not None:
                bid, cached = child
                n = min(len(ours), len(cached))
                eq = np.flatnonzero(ours[:n] != cached[:n])
                d = int(eq[0]) if eq.size else n
                if d > 0:
                    copy_src, n_copied = bid, d
                    self._refs[bid] += 1
        new = self._take(n_real - len(run))
        if new is None:
            for b in run:
                self._refs[b] -= 1
            if copy_src is not None:
                self._refs[copy_src] -= 1
            return None
        self._tables[row_id] = list(run) + new
        self.n_hits += len(run)
        self.n_prefilled += len(new)
        if copy_src is not None:
            self.n_partial_copies += 1
        self._note_peak()
        return StagePlan(table=list(run) + new, n_shared=len(run),
                         n_new=len(new), copy_src=copy_src,
                         n_copied=n_copied)

    def copy_done(self, block_id: int) -> None:
        """Drop the transient reference :meth:`stage` holds on a
        ``copy_src`` block once the device copy has been dispatched.
        Skipping this leaks the reference — :meth:`leak_report`
        catches it."""
        self._decref(block_id)

    def insert_cached(self, row_id, tokens) -> int:
        """Publish the row's FULL blocks into the trie (the trie holds
        its own reference).  Partial last blocks stay private; already
        cached prefixes are left to the first writer.  Returns how
        many blocks were newly cached."""
        if not self.share:
            return 0
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        table = self._tables[row_id]
        added = 0
        for j in range(len(tokens) // self.block):
            bid = table[j]
            if bid in self._trie:
                continue
            if self._trie.insert(tokens, j, bid):
                self._refs[bid] += 1
                added += 1
        return added

    # -- release ------------------------------------------------------- #

    def _decref(self, block_id: int) -> None:
        r = self._refs.get(block_id)
        if r is None:
            raise RuntimeError(
                f"double free: block {block_id} has no holders")
        if r > 1:
            self._refs[block_id] = r - 1
            return
        del self._refs[block_id]
        self._free.append(block_id)

    def free_row(self, row_id) -> int:
        """Release the row's references; returns how many blocks
        actually came FREE (shared blocks survive their other
        holders).  Unknown rows free nothing — evictions are
        idempotent, never a double free."""
        ids = self._tables.pop(row_id, None)
        if not ids:
            return 0
        before = len(self._free)
        for b in reversed(ids):
            self._decref(b)
        return len(self._free) - before

    def evict_block(self, block_id: int) -> None:
        """Force a CACHE eviction of one block.  Refuses while any row
        still holds it (refcount > 1): shared content under a live
        table must never return to the free list."""
        if block_id not in self._trie:
            raise ValueError(f"block {block_id} is not cached")
        if self._refs.get(block_id, 0) > 1:
            raise RuntimeError(
                f"block {block_id} is shared (refcount "
                f"{self._refs[block_id]}): eviction refused while "
                "other holders remain")
        self._trie.drop_block(block_id)
        self._decref(block_id)

    def reclaim(self, n: int) -> int:
        """Drop least-recently-used CACHE-ONLY blocks until ``n`` came
        free (or no candidates remain); rows' blocks are untouchable.
        Returns the number actually freed."""
        freed = 0
        for bid in self._trie.lru_blocks():
            if freed >= n:
                break
            if self._refs.get(bid, 0) != 1:
                continue                    # a row still holds it
            self._trie.drop_block(bid)
            self._decref(bid)
            freed += 1
            self.n_reclaimed += 1
        return freed

    def fork_for_write(self, row_id, idx: int) -> Optional[int]:
        """Copy-on-write: make the row's ``idx``-th block privately
        writable.  A block with other holders (another row or the
        trie) is swapped for a fresh allocation — the caller owes the
        device copy (:func:`~chainermn_tpu.serving.kv_blocks.
        copy_block`) — and the shared original keeps its other
        holders.  Returns the NEW block id, or ``None`` when the
        block was already private (no fork needed).  Raises when the
        pool cannot supply the copy even after reclaim."""
        table = self._tables[row_id]
        bid = table[idx]
        if self._refs[bid] == 1 and bid not in self._trie:
            return None
        new = self._take(1)
        if new is None:
            raise RuntimeError(
                f"copy-on-write fork of block {bid} needs a free "
                "block and the pool has none")
        table[idx] = new[0]
        self._decref(bid)
        self.n_forks += 1
        self._note_peak()
        return new[0]

    # -- wire forms (the engine's program inputs) ---------------------- #

    def padded_table(self, row_id, width: int, *,
                     align: str = "right") -> np.ndarray:
        """The row's table padded with -1 into ``width`` int32
        entries.  ``align="left"`` (real ids first) is the scatter
        form for left-aligned staging; ``align="right"`` keeps the
        ``BlockAllocator`` wire contract."""
        ids = self._tables[row_id]
        if len(ids) > width:
            raise ValueError(
                f"row {row_id!r} holds {len(ids)} blocks > width {width}")
        out = np.full((width,), -1, np.int32)
        if ids:
            if align == "left":
                out[:len(ids)] = np.asarray(ids, np.int32)
            elif align == "right":
                out[width - len(ids):] = np.asarray(ids, np.int32)
            else:
                raise ValueError(f"align={align!r} not in left/right")
        return out

    def flat_gather_index(self, row_id, pq: int, prompt_len: int, *,
                          align: str = "right") -> np.ndarray:
        """The admit gather's position-level index (``Pq``,): token
        ``i`` reads pool position ``table[i // block] * block +
        i % block``.  ``align="right"`` puts token ``i`` at chunk
        position ``pq - prompt_len + i`` (the legacy padded-lane
        layout); ``align="left"`` at position ``i`` (the ragged
        engine's origin-0 lanes).  Out-of-prompt positions are -1
        (clamped garbage the attention window never reads)."""
        table = self._tables[row_id]
        out = np.full((pq,), -1, np.int32)
        i = np.arange(prompt_len)
        flat = (np.asarray(table, np.int32)[i // self.block]
                * self.block + i % self.block)
        if align == "left":
            out[:prompt_len] = flat
        elif align == "right":
            out[pq - prompt_len:] = flat
        else:
            raise ValueError(f"align={align!r} not in left/right")
        return out

    # -- auditing ------------------------------------------------------ #

    def stats(self) -> dict:
        total = self.n_hits + self.n_prefilled
        return {
            "prefix_hits": self.n_hits,
            "prefix_prefilled": self.n_prefilled,
            "prefix_hit_rate": self.n_hits / total if total else 0.0,
            "prefix_forks": self.n_forks,
            "prefix_partial_copies": self.n_partial_copies,
            "prefix_reclaimed": self.n_reclaimed,
            "cached_blocks": self.n_cached,
            "shared_blocks": self.n_shared_blocks,
            "peak_blocks_used": self.peak_blocks_used,
            "peak_row_blocks": self.peak_row_blocks,
        }

    def leak_report(self) -> List[str]:
        """Refcount-invariant audit; empty means clean.  With no rows
        live, every block must be either on the free list or cached
        with exactly the trie's one reference — anything else is a
        leaked or double-counted block."""
        problems = []
        held = collections.Counter()
        for row, ids in self._tables.items():
            held.update(ids)
        for bid in self._trie.lru_blocks():
            held[bid] += 1
        for bid, r in self._refs.items():
            if held[bid] != r:
                problems.append(
                    f"block {bid}: refcount {r} != {held[bid]} holders")
            if bid in self._free:
                problems.append(f"block {bid}: on free list while held")
        for bid, n in held.items():
            if bid not in self._refs:
                problems.append(
                    f"block {bid}: {n} holders but no refcount")
        if len(self._free) + len(self._refs) != self.n_blocks:
            problems.append(
                f"pool imbalance: {len(self._free)} free + "
                f"{len(self._refs)} held != {self.n_blocks}")
        return problems


# --------------------------------------------------------------------- #
# cache snapshot (export / import, CRC-guarded)
# --------------------------------------------------------------------- #
#
# A restarted or rejoining replica starting COLD is a double loss: it
# pays re-prefill for every request the dead replica had cached, and
# the fleet router's prefix-placement signal goes dark exactly when
# traffic is being re-balanced.  The snapshot is the fix: the trie's
# cached prefixes travel as plain token lists (the trie key IS the
# whole token prefix, so the map reconstructs from tokens alone — no
# block ids, which are meaningless across a reset pool).  Only MAXIMAL
# prefixes ship; re-inserting a maximal prefix re-creates every
# ancestor block.  Like the autotune plan, the payload carries a
# format version (unknown -> empty, never crash) and a CRC32 over the
# canonical content (corruption -> ValueError, never silent garbage).

PREFIX_SNAPSHOT_VERSION = 1


def _snapshot_crc(block: int, prefixes: List[List[int]]) -> int:
    body = json.dumps({"block": block, "prefixes": prefixes},
                      sort_keys=True, separators=(",", ":"))
    return zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF


def prefix_snapshot(pool_or_trie) -> dict:
    """Export a :class:`PrefixTrie`'s cached content as a JSON-safe,
    CRC-guarded payload.  Accepts the trie or the owning
    :class:`RefcountedBlockPool`."""
    trie = getattr(pool_or_trie, "_trie", pool_or_trie)
    keys = sorted(trie._map.keys())
    maximal: List[bytes] = []
    for i, key in enumerate(keys):
        # sorted bytes put any extension right after its prefix; a key
        # is maximal iff its successor does not extend it
        if i + 1 < len(keys) and keys[i + 1][:len(key)] == key:
            continue
        maximal.append(key)
    prefixes = [np.frombuffer(k, np.int32).tolist() for k in maximal]
    return {
        "format_version": PREFIX_SNAPSHOT_VERSION,
        "block": trie.block,
        "prefixes": prefixes,
        "crc32": _snapshot_crc(trie.block, prefixes),
    }


def load_prefix_snapshot(payload: dict) -> List[np.ndarray]:
    """Decode a :func:`prefix_snapshot` payload back into token-prefix
    arrays (for ``ServingEngine.import_prefixes``).  An unknown format
    version returns ``[]`` (forward-compatible, like the autotune
    plan); a CRC mismatch raises ``ValueError`` (corruption must be
    loud)."""
    if int(payload.get("format_version", -1)) \
            != PREFIX_SNAPSHOT_VERSION:
        return []
    block = int(payload["block"])
    prefixes = [[int(t) for t in p] for p in payload["prefixes"]]
    got = _snapshot_crc(block, prefixes)
    want = int(payload["crc32"])
    if got != want:
        raise ValueError(
            f"prefix snapshot CRC mismatch: computed {got:#010x}, "
            f"recorded {want:#010x}")
    return [np.asarray(p, np.int32) for p in prefixes]
