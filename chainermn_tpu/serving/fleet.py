"""Fault-tolerant serving fleet: prefix-aware routing over N replicas.

One :class:`~chainermn_tpu.serving.engine.ServingEngine` is a single
point of failure — the exact all-or-nothing fault model the training
side spent three PRs burying (fallback resume, elastic membership,
live resize).  This module is the serving tier's counterpart: a
:class:`FleetRouter` fronting N in-process engine replicas, built
FAILURE-FIRST — a replica dying, flapping, or browning out is an
absorbed event, not an outage.

**Routing.**  Placement is prefix-cache-aware: a request is scored
against each replica's :class:`~chainermn_tpu.serving.prefix_cache.
PrefixTrie` (how many leading full blocks of its prompt are already
cached there) and routed to the replica that can skip the most
prefill.  COLD prefixes (no trie evidence anywhere yet — the first
wave of a new system prompt lands before any prefill completes) are
anchored by a deterministic hash of the prompt's leading block, so
the wave converges on one replica instead of scattering by load-race;
ties beyond that fall to per-replica
:class:`~chainermn_tpu.serving.admission.
ServiceTimePredictor`-fed least-loaded fallback and session affinity
for multi-turn traffic (``submit(session=...)`` sticks to the replica
whose cache holds the conversation).  ``placement="round_robin"`` and
``"oblivious"`` (least-loaded only, cache-blind) exist as bench
baselines.

**Health.**  Each replica runs a watchdog-style state machine —
``healthy → suspect → dead → rejoining`` — driven by its step
heartbeat: a step that raises (or overruns ``dead_after``) kills the
replica; one that overruns ``suspect_after`` marks it suspect, and
``suspect_strikes`` consecutive slow steps escalate to dead.  A
revived replica REJOINS under flap damping: the hold before it takes
traffic again grows exponentially with its death count, so a flapping
replica converges to out-of-rotation instead of whipsawing the
placement signal.

**Failover.**  Replica death is a first-class path, not an exception:
queued requests migrate to a survivor through the PR 12
``export_queue``/``import_queue`` primitives (timestamps intact);
ACTIVE rows are salvaged from the dead engine's host token mirror —
their committed greedy prefix becomes part of the re-dispatch prompt,
so the survivor re-prefills cheaply (prefix cache) and continues the
EXACT solo decode (committed prefix + re-dispatched suffix is
token-bitwise the oracle, pinned by drill).  Completion delivery is
idempotent: the fleet delivers each request id exactly once, whatever
hedges, retries, or failovers raced.

**Retries and hedging.**  Failure-driven re-dispatches take bounded
exponential backoff AND a fleet-wide :class:`RetryBudget` (the gRPC
token-bucket shape: capacity spent per retry, refilled per success) —
a persistent failure burns the budget and degrades to shedding
instead of amplifying into a retry storm.  Optional HEDGED dispatch
covers the tail: a request outstanding past ``hedge_after`` seconds
is duplicated onto a second replica, first completion wins, and the
loser is cancelled through ``cancel(rid)`` (greedy decode makes the
copies token-identical, so hedging never changes output).

**Degradation.**  Fleet admission folds the per-replica predictors
into one global decision: when the predicted fleet-wide queue wait
exceeds ``brown_out_after``, below-tier priority classes are shed
``"overload"`` at the door — a brown-out shorts low-priority traffic
instead of timing everyone out.

Observability rides the existing planes: ``fleet/route``,
``fleet/failover``, ``fleet/hedge_won`` / ``fleet/hedge_lost``,
``fleet/retries``, ``fleet/sheds`` counters and the
``fleet/replica_state`` gauge in the metrics registry, a
``fleet/failover`` span and ``fleet/replica_state`` transition
markers in the flight recorder, and :meth:`FleetRouter.status` as a
statusz section (``server.add_section("fleet", router)``).  Chaos
drills script replica kill/slow/flap through
:meth:`~chainermn_tpu.testing.FaultInjector.attach_fleet`.  See
docs/SERVING.md "Fleet" and docs/RESILIENCE.md.
"""

from __future__ import annotations

import dataclasses
import math
import time
import zlib
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from chainermn_tpu.utils.metrics import get_registry
from chainermn_tpu.utils.telemetry import get_recorder

from .admission import ShedCompletion
from .engine import Completion, Request, ServingEngine
from .prefix_cache import load_prefix_snapshot, prefix_snapshot

__all__ = ["FleetRouter", "ReplicaHandle", "RetryBudget",
           "REPLICA_STATES"]

#: The replica health state machine's states, in escalation order.
REPLICA_STATES = ("healthy", "suspect", "dead", "rejoining")

#: Placement modes (``"prefix"`` is the production one; the others are
#: bench baselines).
PLACEMENTS = ("prefix", "round_robin", "oblivious")


class RetryBudget:
    """Fleet-wide retry token bucket (the gRPC retry-throttling
    shape): every failure-driven re-dispatch or hedge SPENDS one
    token, every successfully served request REFILLS ``refill``
    tokens (capped at ``capacity``).  Under a persistent failure the
    bucket drains and further retries are denied — the router then
    sheds instead of amplifying the failure into a retry storm.
    Successes keep a trickle flowing, so isolated failures always
    retry."""

    def __init__(self, capacity: float = 10.0, refill: float = 0.1):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        if refill < 0:
            raise ValueError(f"refill={refill} must be >= 0")
        self.capacity = float(capacity)
        self.refill = float(refill)
        self.tokens = float(capacity)
        self.spent = 0
        self.denied = 0

    def on_success(self) -> None:
        self.tokens = min(self.capacity, self.tokens + self.refill)

    def try_spend(self) -> bool:
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            self.spent += 1
            return True
        self.denied += 1
        return False

    def refund(self) -> None:
        """Return a token whose retry/hedge was never actually
        placed (the chosen replica refused the dispatch) — the
        budget meters placed re-dispatches, not attempts, or a
        refusing replica would drain it with zero retries flowing."""
        self.tokens = min(self.capacity, self.tokens + 1.0)
        self.spent = max(self.spent - 1, 0)

    def snapshot(self) -> dict:
        return {"capacity": self.capacity, "refill": self.refill,
                "tokens": self.tokens, "spent": self.spent,
                "denied": self.denied}


class ReplicaHandle:
    """One replica's router-side identity: the engine, its health
    state, and the flap-damping history.  The router owns every
    transition; the handle is bookkeeping."""

    def __init__(self, name: str, engine: ServingEngine):
        self.name = str(name)
        self.engine = engine
        self.state = "healthy"
        self.slow_strikes = 0       # consecutive suspect-slow steps
        self.deaths = 0             # lifetime kill count (flap signal)
        self.rejoin_at: Optional[int] = None   # fleet step gate
        self.rejoin_hold = 0        # the damped hold last applied
        self.steps = 0
        self.step_seconds = 0.0
        self.last_error = ""

    @property
    def alive(self) -> bool:
        return self.state != "dead"

    def taking_traffic(self, fleet_step: int) -> bool:
        """Whether placement may target this replica now: healthy or
        suspect (degraded but serving); a rejoining replica holds
        until its damped gate expires."""
        if self.state in ("healthy", "suspect"):
            return True
        if self.state == "rejoining":
            return self.rejoin_at is not None \
                and fleet_step >= self.rejoin_at
        return False

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "deaths": self.deaths,
            "steps": self.steps,
            "step_seconds": self.step_seconds,
            "rejoin_at": self.rejoin_at,
            "rejoin_hold": self.rejoin_hold,
            "queue_depth": len(self.engine._queue),
            "active": self.engine.n_active,
            "last_error": self.last_error,
        }


@dataclasses.dataclass(eq=False)
class _Flight:
    """Router-side state of one in-flight fleet request.

    ``committed`` is the salvaged greedy prefix (tokens the request
    had generated on a replica that later died); ``dispatches`` maps
    replica name -> ``{"kind": "primary"|"hedge"|"migrated",
    "base": n}`` where ``base`` is how many committed tokens were
    folded into THAT dispatch's prompt (delivery re-prepends
    ``committed[:base]`` so merged output is the full stream)."""

    fid: str
    prompt: np.ndarray
    max_new: int
    priority: int = 0
    tenant: Optional[str] = None
    deadline: Optional[float] = None
    session: Optional[str] = None
    sampling: Optional[object] = None
    t_submit: float = 0.0
    t_dispatch: float = 0.0
    committed: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0,), np.int32))
    dispatches: Dict[str, dict] = dataclasses.field(default_factory=dict)
    hedged: bool = False
    retries: int = 0
    not_before: float = 0.0
    cancel_requested: bool = False


class FleetRouter:
    """Prefix-aware, failure-absorbing router over N in-process
    :class:`~chainermn_tpu.serving.engine.ServingEngine` replicas.

    Args:
      engines: the replica engines (>= 1; homogeneous configs are
        assumed for placement math but not enforced).
      names: replica names (default ``replica0..N-1``).
      placement: ``"prefix"`` (cache-aware, the default),
        ``"round_robin"``, or ``"oblivious"`` (least-loaded only).
      hedge_after: seconds an un-completed request waits before a
        duplicate dispatch to a second replica (``None`` disables
        hedging).  The loser is cancelled; delivery stays
        exactly-once.
      retry_budget: the fleet-wide :class:`RetryBudget` (one is
        created by default).  Hedges and failure-driven retries spend
        it; successes refill it.
      max_retries: per-request cap on failure-driven re-dispatches.
      backoff_base / backoff_cap: bounded exponential backoff between
        a request's retries (``base * 2**(retries-1)``, capped).
      suspect_after: a replica step slower than this (seconds) marks
        it suspect; ``suspect_strikes`` consecutive slow steps
        escalate to dead.  ``None`` disables slowness detection.
      dead_after: a step slower than this is an immediate death
        (hard watchdog deadline; ``None`` disables).
      rejoin_hold: base fleet-step hold before a revived replica
        takes traffic again.
      flap_damping: hold multiplier per prior death — the k-th rejoin
        holds ``rejoin_hold * flap_damping**(k-1)`` steps (capped at
        ``max_hold``), so a flapping replica converges out of
        rotation.
      brown_out_after: predicted fleet-wide queue wait (seconds,
        folded from the per-replica predictors) beyond which arriving
        requests with ``priority > protect_priority`` are shed
        ``"overload"`` at the door.  ``None`` disables.
      protect_priority: the most-important class still sheltered from
        brown-out shedding (default 0, matching
        ``AdmissionController``).
      warm_on_rejoin: import the dead replica's CRC-guarded prefix
        snapshot when reviving it, so it rejoins warm and the
        placement signal survives the failover.
      max_sessions: LRU cap on remembered session -> replica homes
        (affinity is a routing hint; evicting an old session only
        costs a re-learned placement, never correctness).
      max_records: cap on retained terminal records — the oldest are
        dropped past it so :meth:`request_records` stays bounded on a
        long-running fleet.  ``None`` (the default) retains every
        record, which grows without bound by design: offline drills
        and benches audit the full stream.  (The delivered-id set
        backing idempotent delivery is always retained — it is the
        exactly-once contract, a few bytes per request.)
      clock: time source (``time.perf_counter``); injectable for
        deterministic drills.
    """

    def __init__(self, engines: Sequence[ServingEngine], *,
                 names: Optional[Sequence[str]] = None,
                 placement: str = "prefix",
                 hedge_after: Optional[float] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 max_retries: int = 3,
                 backoff_base: float = 0.0,
                 backoff_cap: float = 1.0,
                 suspect_after: Optional[float] = None,
                 dead_after: Optional[float] = None,
                 suspect_strikes: int = 2,
                 rejoin_hold: int = 2,
                 flap_damping: float = 2.0,
                 max_hold: int = 64,
                 brown_out_after: Optional[float] = None,
                 protect_priority: int = 0,
                 warm_on_rejoin: bool = True,
                 max_sessions: int = 4096,
                 max_records: Optional[int] = None,
                 clock=time.perf_counter):
        engines = list(engines)
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement={placement!r} not in {PLACEMENTS}")
        if names is None:
            names = [f"replica{i}" for i in range(len(engines))]
        if len(names) != len(engines) or len(set(names)) != len(names):
            raise ValueError("names must be unique, one per engine")
        if hedge_after is not None and hedge_after < 0:
            raise ValueError(f"hedge_after={hedge_after} must be >= 0")
        if max_retries < 0:
            raise ValueError(f"max_retries={max_retries} must be >= 0")
        if suspect_strikes < 1:
            raise ValueError(
                f"suspect_strikes={suspect_strikes} must be >= 1")
        if rejoin_hold < 0 or max_hold < rejoin_hold:
            raise ValueError(
                f"need 0 <= rejoin_hold ({rejoin_hold}) <= max_hold "
                f"({max_hold})")
        if flap_damping < 1.0:
            raise ValueError(
                f"flap_damping={flap_damping} must be >= 1 (damping "
                "never shortens the hold)")
        if max_sessions < 1:
            raise ValueError(
                f"max_sessions={max_sessions} must be >= 1")
        if max_records is not None and max_records < 1:
            raise ValueError(
                f"max_records={max_records} must be >= 1 (or None "
                "for unbounded retention)")
        self.replicas = [ReplicaHandle(n, e)
                         for n, e in zip(names, engines)]
        self._by_name = {h.name: h for h in self.replicas}
        self.placement = placement
        self.hedge_after = hedge_after
        self.retry_budget = retry_budget or RetryBudget()
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.suspect_strikes = int(suspect_strikes)
        self.rejoin_hold = int(rejoin_hold)
        self.flap_damping = float(flap_damping)
        self.max_hold = int(max_hold)
        self.brown_out_after = brown_out_after
        self.protect_priority = int(protect_priority)
        self.warm_on_rejoin = bool(warm_on_rejoin)
        self.max_sessions = int(max_sessions)
        self.max_records = (None if max_records is None
                            else int(max_records))
        self._clock = clock
        self.step_count = 0
        self._rr = 0
        self._next_fid = 0
        self._flights: Dict[str, _Flight] = {}
        self._pending: List[str] = []
        # terminal records produced OUTSIDE a step() heartbeat
        # (dispatch-time sheds, pending cancels) park here until the
        # next step() drains them — every asynchronous terminal flows
        # through the step() stream exactly once
        self._outbox: List[Union[Completion, ShedCompletion]] = []
        self._delivered: set = set()
        self._records: List[Union[Completion, ShedCompletion]] = []
        self._sessions: Dict[str, str] = {}
        self._snapshots: Dict[str, dict] = {}
        self.n_failovers = 0
        self.n_hedges = 0
        self.n_hedge_won = 0
        self.n_hedge_lost = 0
        self.n_retries = 0
        self.n_sheds = 0
        self.n_migrated = 0

    # ------------------------------------------------------------------ #
    # submission / cancellation
    # ------------------------------------------------------------------ #

    @property
    def n_healthy(self) -> int:
        return sum(h.state in ("healthy", "suspect")
                   for h in self.replicas)

    @property
    def idle(self) -> bool:
        return not self._flights and not self._outbox

    def submit(self, prompt, max_new: Optional[int] = None, *,
               priority: int = 0, tenant: Optional[str] = None,
               deadline: Optional[float] = None,
               timeout: Optional[float] = None,
               session: Optional[str] = None,
               sampling=None) -> Union[str, ShedCompletion]:
        """Queue one request with the fleet; returns its fleet id
        (``f<n>``) — or a reason-coded
        :class:`~chainermn_tpu.serving.admission.ShedCompletion` when
        fleet admission turns it away (brown-out).  The id doubles as
        the per-replica engine request id, so it is the ONE identity a
        request carries across failovers, hedges and migrations.

        ``session`` names a multi-turn conversation: later submits
        with the same session stick to the replica whose prefix cache
        holds the earlier turns (re-learned on failover)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        now = self._clock()
        if timeout is not None:
            if deadline is not None:
                raise ValueError("give deadline= OR timeout=, not both")
            if timeout <= 0:
                raise ValueError(f"timeout={timeout} must be > 0")
            deadline = now + timeout
        if max_new is None:
            max_new = self.replicas[0].engine.default_max_new
        fid = f"f{self._next_fid}"
        self._next_fid += 1
        fl = _Flight(fid=fid, prompt=prompt, max_new=int(max_new),
                     priority=int(priority), tenant=tenant,
                     deadline=deadline, session=session,
                     sampling=sampling, t_submit=now)
        reason = self._fleet_admission(fl)
        if reason is not None:
            return self._shed_flight(fl, reason,
                                     detail="fleet brown-out: predicted "
                                            "queue wait over threshold")
        self._flights[fid] = fl
        self._pending.append(fid)
        self._dispatch_pending()
        return fid

    def cancel(self, fid: str) -> bool:
        """Cancel a live fleet request on every replica carrying a
        copy; a pending (undispatched) request sheds ``"cancelled"``
        immediately.  False when the id is not live."""
        fl = self._flights.get(fid)
        if fl is None:
            return False
        fl.cancel_requested = True
        if not fl.dispatches:
            self._pending = [f for f in self._pending if f != fid]
            rec = self._shed_flight(fl, "cancelled")
            del self._flights[fid]
            self._outbox.append(rec)
            return True
        for name in list(fl.dispatches):
            h = self._by_name[name]
            if h.alive:
                try:
                    h.engine.cancel(fid)
                except Exception:   # noqa: BLE001 — dying replica
                    pass
        return True

    # ------------------------------------------------------------------ #
    # fleet admission (graceful degradation)
    # ------------------------------------------------------------------ #

    def predicted_queue_wait(self) -> Optional[float]:
        """The global queue-wait estimate fleet admission keys on:
        total live backlog tokens (every serving replica's queue +
        active remainders, plus the router's own pending requests)
        drained at the fleet's aggregate decode rate, with the TPOT
        folded from the per-replica predictors.  ``None`` while no
        replica has evidence — shedding needs evidence, fleet-wide
        exactly like per-engine."""
        serving = [h for h in self.replicas
                   if h.state in ("healthy", "suspect")]
        if not serving:
            return None
        backlog = 0
        slots = 0
        tpots = []
        for h in serving:
            backlog += h.engine._backlog_tokens()
            slots += h.engine.n_slots
            ctrl = h.engine.admission
            if ctrl is not None:
                t = ctrl.predictor.tpot()
                if t is not None:
                    tpots.append(t)
        for fid in self._pending:
            backlog += self._flights[fid].max_new
        if not tpots:
            return None
        return (sum(tpots) / len(tpots)) * backlog / max(slots, 1)

    def _fleet_admission(self, fl: _Flight) -> Optional[str]:
        if self.brown_out_after is None:
            return None
        if fl.priority <= self.protect_priority:
            return None
        wait = self.predicted_queue_wait()
        if wait is not None and wait > self.brown_out_after:
            return "overload"
        return None

    # ------------------------------------------------------------------ #
    # placement
    # ------------------------------------------------------------------ #

    def _prefix_score(self, h: ReplicaHandle,
                      prompt: np.ndarray) -> int:
        """Cached leading full blocks of ``prompt`` in the replica's
        trie — the prefill this placement would skip."""
        try:
            return len(h.engine._alloc._trie.lookup_run(prompt))
        except Exception:       # noqa: BLE001 — scoring must not kill
            return 0

    def _load_score(self, h: ReplicaHandle) -> float:
        """Predicted seconds of queue wait on this replica (its own
        predictor's TPOT over its live backlog); falls back to raw
        backlog tokens per slot while the predictor is cold."""
        eng = h.engine
        backlog = eng._backlog_tokens()
        tpot = None
        if eng.admission is not None:
            tpot = eng.admission.predictor.tpot()
        if tpot is None:
            return backlog / max(eng.n_slots, 1)
        return tpot * backlog / max(eng.n_slots, 1)

    def _placement_order(self, fl: _Flight,
                         exclude: Sequence[str] = ()
                         ) -> List[ReplicaHandle]:
        cands = [h for h in self.replicas
                 if h.taking_traffic(self.step_count)
                 and h.name not in exclude]
        if not cands:
            return []
        if self.placement == "round_robin":
            k = self._rr % len(cands)
            self._rr += 1
            return cands[k:] + cands[:k]
        order = {h.name: i for i, h in enumerate(self.replicas)}
        if self.placement == "oblivious":
            ranked = sorted(
                cands, key=lambda h: (self._load_score(h),
                                      order[h.name]))
        else:                           # "prefix"
            block = max(self.replicas[0].engine.block, 1)
            full = fl.prompt.shape[0] // block
            # deterministic hash affinity anchors COLD prefixes: the
            # first wave of a new system prompt lands before any
            # prefill has populated a trie, so trie evidence alone
            # would scatter it by load (whoever wins the race keeps
            # the prefix) — hashing the leading block gives every
            # replica-set member the same verdict from request #1,
            # and live trie evidence still dominates once it exists
            if full >= 1:
                lead = np.ascontiguousarray(
                    fl.prompt[:block], np.int32).tobytes()
                anchor = zlib.crc32(lead) % len(self.replicas)
            else:
                anchor = None
            anchor_name = (self.replicas[anchor].name
                           if anchor is not None else None)
            ranked = sorted(
                cands,
                key=lambda h: (-min(self._prefix_score(h, fl.prompt),
                                    full),
                               h.name != anchor_name,
                               self._load_score(h), order[h.name]))
            sticky = self._sessions.get(fl.session)
            if sticky is not None:
                home = self._by_name.get(sticky)
                if home is not None and home in ranked:
                    ranked.remove(home)
                    ranked.insert(0, home)
        return ranked

    # ------------------------------------------------------------------ #
    # dispatch
    # ------------------------------------------------------------------ #

    def _dispatch(self, fl: _Flight, h: ReplicaHandle,
                  kind: str) -> Optional[ShedCompletion]:
        """Submit the flight to one replica.  Returns ``None`` on
        success or the engine's ShedCompletion on rejection (the
        caller tries the next candidate)."""
        base = int(fl.committed.shape[0])
        prompt = fl.prompt
        remaining = fl.max_new - base
        if remaining <= 0:
            # the committed prefix already fills the token budget —
            # the flight IS complete; submitting would force at least
            # one extra generated token past max_new.  Deliver it.
            if self._finalize_if_complete(fl, h, self._outbox,
                                          self._clock()):
                return None
        if base:
            prompt = np.concatenate([fl.prompt, fl.committed])
            if prompt.shape[0] > h.engine.max_prompt:
                # the committed prefix no longer fits as prompt —
                # re-decode from scratch (greedy: same tokens)
                prompt, base, remaining = fl.prompt, 0, fl.max_new
        try:
            res = h.engine.submit(prompt, max_new=max(remaining, 1),
                                  request_id=fl.fid,
                                  priority=fl.priority,
                                  tenant=fl.tenant,
                                  deadline=fl.deadline,
                                  sampling=fl.sampling)
        except ValueError as err:
            # the engine refused to even queue it (rid already live
            # there — e.g. a surviving hedge copy — or the request
            # violates its limits); a refusal, not a router crash
            return ShedCompletion(
                rid=fl.fid, prompt=fl.prompt, reason="overload",
                t_submit=fl.t_submit, t_shed=self._clock(),
                max_new=fl.max_new, priority=fl.priority,
                tenant=fl.tenant,
                detail=f"submit refused by {h.name}: {err}")
        if isinstance(res, ShedCompletion):
            return res
        fl.dispatches[h.name] = {"kind": kind, "base": base}
        fl.t_dispatch = self._clock()
        if fl.session is not None:
            # LRU: re-insertion moves the session to the young end;
            # overflow evicts the stalest home (a routing hint only)
            self._sessions.pop(fl.session, None)
            self._sessions[fl.session] = h.name
            while len(self._sessions) > self.max_sessions:
                del self._sessions[next(iter(self._sessions))]
        get_registry().inc("fleet/route")
        return None

    def _dispatch_pending(self) -> None:
        if not self._pending:
            return
        now = self._clock()
        if not any(h.alive for h in self.replicas):
            # total outage: fail fast rather than queue into the void
            for fid in list(self._pending):
                fl = self._flights.pop(fid)
                self._outbox.append(self._shed_flight(
                    fl, "overload", detail="no live replicas"))
            self._pending.clear()
            return
        still: List[str] = []
        for fid in self._pending:
            fl = self._flights.get(fid)
            if fl is None or fid in self._delivered:
                continue            # settled while parked (cancel race)
            if fl.not_before > now:
                still.append(fid)
                continue
            # a replica already carrying a copy (surviving hedge /
            # migrated twin) must not receive a second one — its
            # engine would refuse the duplicate rid
            order = self._placement_order(fl,
                                          exclude=list(fl.dispatches))
            if not order:
                still.append(fid)       # all holds; retry next step
                continue
            last_shed = None
            placed = False
            for h in order:
                shed = self._dispatch(fl, h, kind="primary")
                if shed is None:
                    placed = True
                    break
                last_shed = shed
            if placed:
                continue
            if fl.dispatches:
                # every candidate refused, but a live copy still
                # carries the request — its verdict will arrive
                continue
            # every candidate replica refused — the fleet verdict is
            # the last engine's reason-coded shed
            del self._flights[fid]
            last_shed.t_submit = fl.t_submit
            self.n_sheds += 1
            get_registry().inc("fleet/sheds")
            self._deliver_record(fl, last_shed)
            self._outbox.append(last_shed)
        self._pending = still

    # ------------------------------------------------------------------ #
    # stepping, health, delivery
    # ------------------------------------------------------------------ #

    def _step_replica(self, h: ReplicaHandle):
        """One replica heartbeat — separated out so
        ``FaultInjector.attach_fleet`` can wrap it (kill/slow/flap
        drills) without the router knowing it is under test."""
        return h.engine.step()

    def step(self) -> List[Union[Completion, ShedCompletion]]:
        """One fleet iteration: promote rejoiners whose hold expired,
        dispatch pending requests, heartbeat every live replica
        (collecting and delivering its terminal records), fail over
        any replica that died this tick, then hedge the stragglers.
        Returns this iteration's fleet-level terminal records —
        each fleet id appears EXACTLY ONCE across all steps."""
        self.step_count += 1
        out: List[Union[Completion, ShedCompletion]] = []
        if self._outbox:
            out.extend(self._outbox)
            self._outbox.clear()
        self._promote_rejoining()
        self._dispatch_pending()
        died: List[ReplicaHandle] = []
        for h in self.replicas:
            if not h.alive:
                continue
            t0 = self._clock()
            try:
                recs = self._step_replica(h)
            except Exception as err:    # noqa: BLE001 — that IS death
                h.last_error = f"{type(err).__name__}: {err}"
                self._set_state(h, "dead")
                h.deaths += 1
                died.append(h)
                continue
            dt = self._clock() - t0
            h.steps += 1
            h.step_seconds += dt
            if self._note_step_health(h, dt):
                died.append(h)
            for r in recs:
                self._deliver(h, r, out)
        for h in died:
            self._failover(h, out)
        self._hedge_scan(out)
        if self._outbox:                # sheds parked mid-step
            out.extend(self._outbox)
            self._outbox.clear()
        return out

    def run(self, max_steps: Optional[int] = None
            ) -> List[Union[Completion, ShedCompletion]]:
        """Drive :meth:`step` until every submitted request has been
        delivered (or ``max_steps`` elapsed)."""
        out: List[Union[Completion, ShedCompletion]] = []
        steps = 0
        while not self.idle:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def _note_step_health(self, h: ReplicaHandle, dt: float) -> bool:
        """Heartbeat verdict for one completed step; True when the
        replica just died (deadline overrun / strike-out)."""
        if self.dead_after is not None and dt > self.dead_after:
            h.last_error = (f"step overran the {self.dead_after}s "
                            "death deadline")
            self._set_state(h, "dead")
            h.deaths += 1
            return True
        if self.suspect_after is not None and dt > self.suspect_after:
            h.slow_strikes += 1
            if h.state == "healthy":
                self._set_state(h, "suspect")
            if h.slow_strikes >= self.suspect_strikes:
                h.last_error = (f"{h.slow_strikes} consecutive steps "
                                f"over the {self.suspect_after}s "
                                "suspect threshold")
                self._set_state(h, "dead")
                h.deaths += 1
                return True
            return False
        h.slow_strikes = 0
        if h.state == "suspect":
            self._set_state(h, "healthy")
        return False

    def _set_state(self, h: ReplicaHandle, state: str) -> None:
        if h.state == state:
            return
        h.state = state
        reg = get_registry()
        reg.set("fleet/replica_state", float(self.n_healthy))
        get_recorder().instant("fleet/replica_state", cat="fleet",
                               replica=h.name, state=state,
                               deaths=h.deaths)

    def _promote_rejoining(self) -> None:
        for h in self.replicas:
            if h.state == "rejoining" and h.rejoin_at is not None \
                    and self.step_count >= h.rejoin_at:
                h.slow_strikes = 0
                self._set_state(h, "healthy")

    # ------------------------------------------------------------------ #
    # failover
    # ------------------------------------------------------------------ #

    def _salvage_active(self, h: ReplicaHandle) -> Dict[str, dict]:
        """Read the dead engine's host mirrors: per live row, the
        committed greedy tokens (positions ``[plen, pos]`` of its
        origin-0 lane) and terminal status — the committed log a
        re-dispatch continues from.  Best-effort: an unreadable
        engine salvages nothing (those rows retry from scratch)."""
        eng = h.engine
        salvaged: Dict[str, dict] = {}
        try:
            for s in range(eng.n_slots):
                req = eng._slot_req[s]
                if req is None:
                    continue
                try:
                    row = np.asarray(eng._buf[s])
                    gen = np.array(
                        row[int(eng._plen[s]): int(eng._pos[s]) + 1],
                        np.int32)
                except Exception:   # noqa: BLE001 — device state gone
                    gen = np.zeros((0,), np.int32)
                salvaged[req.rid] = {
                    "tokens": gen,
                    "done": bool(eng._done[s]),
                    "status": eng._slot_status[s],
                }
        except Exception:           # noqa: BLE001 — salvage is bonus
            pass
        return salvaged

    def _failover(self, h: ReplicaHandle,
                  out: List[Union[Completion, ShedCompletion]]) -> None:
        """Absorb one replica death: snapshot its prefix cache (for a
        warm rejoin), migrate its queued requests to a survivor via
        ``export_queue``/``import_queue``, re-dispatch its active
        rows from their committed prefixes, then reset the engine so
        a later revive starts clean."""
        now = self._clock()
        rec = get_recorder()
        reg = get_registry()
        with rec.span("fleet/failover", cat="fleet", replica=h.name,
                      step=self.step_count):
            self.n_failovers += 1
            reg.inc("fleet/failover")
            try:
                self._snapshots[h.name] = prefix_snapshot(
                    h.engine._alloc)
            except Exception:       # noqa: BLE001 — snapshot is bonus
                pass
            salvaged = self._salvage_active(h)
            try:
                exported = h.engine.export_queue()
            except Exception:       # noqa: BLE001
                exported = []
            # forget the dead replica's session homes — the next turn
            # re-learns placement from the survivors' caches
            for sess, name in list(self._sessions.items()):
                if name == h.name:
                    del self._sessions[sess]
            # --- queued requests migrate wholesale ------------------- #
            exported = [r for r in exported if self._forget_dispatch(
                r.rid, h.name)]
            # a hedge copy whose OTHER copy is still live rides that
            # copy — migrating it would plant a duplicate rid on a
            # replica the twin may already occupy (import_queue would
            # refuse the whole batch)
            exported = [r for r in exported
                        if not self._flights[r.rid].dispatches]
            if exported:
                target = self._migration_target()
                migrated = False
                if target is not None:
                    try:
                        target.engine.import_queue(exported)
                        for r in exported:
                            fl = self._flights.get(r.rid)
                            if fl is not None:
                                fl.dispatches[target.name] = {
                                    "kind": "migrated",
                                    "base": self._dispatch_base(fl, r)}
                        self.n_migrated += len(exported)
                        migrated = True
                    except Exception:   # noqa: BLE001 — fall back
                        pass
                if not migrated:
                    # no survivor to adopt the queue: each re-dispatch
                    # is a failure-driven RETRY, so it pays backoff and
                    # budget like one — a replica crash-looping alone
                    # must drain the budget and shed, not spin free
                    for r in exported:
                        fl = self._flights.get(r.rid)
                        if fl is None or r.rid in self._delivered:
                            continue
                        self._retry_or_shed(fl, now, out)
            # --- active rows re-dispatch from their committed log ---- #
            for rid, info in salvaged.items():
                fl = self._flights.get(rid)
                if fl is None or rid in self._delivered:
                    continue
                disp = fl.dispatches.pop(h.name, None)
                if disp is None:
                    continue
                candidate = np.concatenate(
                    [fl.committed[:disp["base"]], info["tokens"]])
                if candidate.shape[0] > fl.committed.shape[0]:
                    fl.committed = candidate
                if fl.dispatches:
                    continue        # a hedge copy is still running
                if self._finalize_if_complete(fl, h, out, now):
                    continue
                self._retry_or_shed(fl, now, out)
            try:
                h.engine.reset()
            except Exception:       # noqa: BLE001 — engine truly gone
                pass

    def _forget_dispatch(self, fid: str, replica: str) -> bool:
        """Drop the dead replica from a flight's dispatch map; True
        when the flight is still live (needs migration)."""
        fl = self._flights.get(fid)
        if fl is None:
            return False
        fl.dispatches.pop(replica, None)
        return fid not in self._delivered

    def _dispatch_base(self, fl: _Flight, req: Request) -> int:
        """How many committed tokens a migrated queued Request's
        prompt already folds in (its prompt may be the original or a
        committed-prefix re-dispatch)."""
        return max(int(req.prompt.shape[0])
                   - int(fl.prompt.shape[0]), 0)

    def _migration_target(self) -> Optional[ReplicaHandle]:
        cands = [h for h in self.replicas
                 if h.taking_traffic(self.step_count)]
        if not cands:
            return None
        order = {h.name: i for i, h in enumerate(self.replicas)}
        return min(cands, key=lambda h: (self._load_score(h),
                                         order[h.name]))

    def _finalize_if_complete(self, fl: _Flight, h: ReplicaHandle,
                              out: list, now: float) -> bool:
        """A salvaged committed prefix that already reached EOS or the
        token budget IS the completion — deliver it instead of
        re-dispatching a zero-token remainder."""
        eos = h.engine.eos_id
        tokens = fl.committed
        hit_eos = False
        if eos >= 0:
            hits = np.nonzero(tokens == eos)[0]
            if hits.size:
                tokens = tokens[:int(hits[0]) + 1]
                hit_eos = True
        if not hit_eos and tokens.shape[0] < fl.max_new:
            return False
        comp = Completion(
            rid=fl.fid, prompt=fl.prompt, tokens=np.array(tokens),
            t_submit=fl.t_submit, t_admit=None, t_first=None,
            t_done=now, slot=-1, status="ok",
            detail=f"salvaged complete from {h.name}")
        del self._flights[fl.fid]
        self.retry_budget.on_success()
        self._deliver_record(fl, comp)
        out.append(comp)
        return True

    def _retry_or_shed(self, fl: _Flight, now: float,
                       out: list) -> None:
        """The bounded-backoff, budget-governed retry decision for a
        flight whose every dispatch just failed."""
        if fl.cancel_requested:
            shed = self._shed_flight(fl, "cancelled")
            del self._flights[fl.fid]
            out.append(shed)
            return
        if fl.retries >= self.max_retries \
                or not self.retry_budget.try_spend():
            shed = self._shed_flight(
                fl, "overload",
                detail=f"retry budget exhausted after {fl.retries} "
                       "retries")
            del self._flights[fl.fid]
            out.append(shed)
            return
        fl.retries += 1
        self.n_retries += 1
        get_registry().inc("fleet/retries")
        backoff = min(self.backoff_cap,
                      self.backoff_base * (2.0 ** (fl.retries - 1)))
        fl.not_before = now + backoff
        if fl.fid not in self._pending:
            self._pending.append(fl.fid)

    # ------------------------------------------------------------------ #
    # hedging
    # ------------------------------------------------------------------ #

    def _hedge_scan(self, out: list) -> None:
        if self.hedge_after is None:
            return
        now = self._clock()
        for fid, fl in list(self._flights.items()):
            if fl.hedged or not fl.dispatches \
                    or len(fl.dispatches) != 1:
                continue
            if now - fl.t_dispatch < self.hedge_after:
                continue
            order = self._placement_order(
                fl, exclude=list(fl.dispatches))
            if not order:
                continue
            if not self.retry_budget.try_spend():
                continue        # budget empty: the tail stays unhedged
            shed = self._dispatch(fl, order[0], kind="hedge")
            if shed is None:
                fl.hedged = True
                self.n_hedges += 1
            else:
                # no hedge was placed: hand the token back, or this
                # flight re-spends one every step while the candidate
                # keeps refusing — draining the budget for nothing
                self.retry_budget.refund()

    # ------------------------------------------------------------------ #
    # delivery (exactly-once)
    # ------------------------------------------------------------------ #

    def _deliver_record(self, fl: _Flight, record) -> None:
        self._delivered.add(fl.fid)
        self._records.append(record)
        if self.max_records is not None \
                and len(self._records) > self.max_records:
            del self._records[:len(self._records) - self.max_records]

    def _shed_flight(self, fl: _Flight, reason: str,
                     detail: str = "") -> ShedCompletion:
        shed = ShedCompletion(
            rid=fl.fid, prompt=fl.prompt, reason=reason,
            t_submit=fl.t_submit, t_shed=self._clock(),
            max_new=fl.max_new, priority=fl.priority,
            tenant=fl.tenant, detail=detail)
        self.n_sheds += 1
        get_registry().inc("fleet/sheds")
        self._deliver_record(fl, shed)
        return shed

    def _deliver(self, h: ReplicaHandle, record, out: list) -> None:
        """Translate one replica terminal record into the fleet's
        exactly-once stream.  Loser copies (hedge/cancel races) and
        records for already-delivered ids are absorbed silently."""
        fid = getattr(record, "rid", None)
        fl = self._flights.get(fid)
        if fl is None or fid in self._delivered:
            return                      # stray: already settled
        disp = fl.dispatches.pop(h.name, None)
        if isinstance(record, ShedCompletion):
            # a queue-side termination on ONE replica.  If another
            # copy is still live the request is not over; if the shed
            # was the only copy, it is the fleet verdict.
            if fl.dispatches:
                return
            if record.reason == "cancelled" \
                    and not fl.cancel_requested:
                # cancelled as a hedge loser, but no live copy left —
                # re-dispatch rather than losing the request (unless
                # its committed prefix already completes it)
                now = self._clock()
                if not self._finalize_if_complete(fl, h, out, now):
                    self._retry_or_shed(fl, now, out)
                return
            del self._flights[fid]
            record.t_submit = fl.t_submit
            self.n_sheds += 1
            get_registry().inc("fleet/sheds")
            self._deliver_record(fl, record)
            out.append(record)
            return
        status = record.status
        if status == "cancelled" and not fl.cancel_requested:
            # hedge loser evicted after losing the race; bank its
            # tokens (greedy: identical to any other copy's) so a
            # rare both-copies-gone re-dispatch resumes, not restarts
            base = disp["base"] if disp else 0
            candidate = np.concatenate(
                [fl.committed[:base],
                 np.asarray(record.tokens, np.int32).reshape(-1)])
            if candidate.shape[0] > fl.committed.shape[0]:
                fl.committed = candidate
            if not fl.dispatches:
                now = self._clock()
                if not self._finalize_if_complete(fl, h, out, now):
                    self._retry_or_shed(fl, now, out)
            return
        if status == "quarantined":
            # replica-side failure of THIS request; other slots kept
            # serving, so the replica is fine — retry elsewhere unless
            # a copy is still live (or the prefix already completes)
            base = disp["base"] if disp else 0
            candidate = np.concatenate(
                [fl.committed[:base], record.tokens])
            if candidate.shape[0] > fl.committed.shape[0]:
                fl.committed = candidate
            if not fl.dispatches:
                now = self._clock()
                if not self._finalize_if_complete(fl, h, out, now):
                    self._retry_or_shed(fl, now, out)
            return
        # "ok" / "timeout" / caller-asked "cancelled": the verdict.
        base = disp["base"] if disp else 0
        if base:
            record.tokens = np.concatenate(
                [fl.committed[:base], record.tokens])
            eos = h.engine.eos_id
            if eos >= 0:
                hits = np.nonzero(record.tokens == eos)[0]
                if hits.size:
                    record.tokens = record.tokens[:int(hits[0]) + 1]
        record.t_submit = fl.t_submit
        losers = list(fl.dispatches)
        del self._flights[fid]
        self._deliver_record(fl, record)
        out.append(record)
        if status == "ok":
            self.retry_budget.on_success()
        if fl.hedged and disp is not None:
            reg = get_registry()
            if disp["kind"] == "hedge":
                self.n_hedge_won += 1
                reg.inc("fleet/hedge_won")
            else:
                self.n_hedge_lost += 1
                reg.inc("fleet/hedge_lost")
        for name in losers:
            loser = self._by_name.get(name)
            if loser is not None and loser.alive:
                try:
                    loser.engine.cancel(fid)
                except Exception:   # noqa: BLE001 — racing a death
                    pass

    # ------------------------------------------------------------------ #
    # revive / rejoin
    # ------------------------------------------------------------------ #

    def revive(self, name: str, *, engine: Optional[ServingEngine]
               = None, warm: Optional[dict] = None) -> ReplicaHandle:
        """Bring a dead replica back as REJOINING: it heartbeats
        immediately but takes no traffic until its flap-damped hold
        expires (``rejoin_hold * flap_damping**(deaths-1)`` fleet
        steps, capped at ``max_hold`` — a flapping replica waits
        exponentially longer each time).  ``engine`` swaps in a
        replacement engine (a real restart); by default the reset
        original is reused.  ``warm`` imports a prefix snapshot
        (default: the one taken at death, when ``warm_on_rejoin``) so
        the replica rejoins with its placement signal intact."""
        h = self._by_name[name]
        if h.state != "dead":
            raise ValueError(f"replica {name!r} is {h.state}, not dead")
        if engine is not None:
            h.engine = engine
        hold = self.rejoin_hold * (
            self.flap_damping ** max(h.deaths - 1, 0))
        h.rejoin_hold = min(self.max_hold, int(math.ceil(hold)))
        h.rejoin_at = self.step_count + h.rejoin_hold
        h.slow_strikes = 0
        h.last_error = ""
        self._set_state(h, "rejoining")
        payload = warm if warm is not None else (
            self._snapshots.get(name) if self.warm_on_rejoin else None)
        if payload:
            try:
                prefixes = load_prefix_snapshot(payload)
                if prefixes:
                    h.engine.import_prefixes(prefixes)
            except ValueError:
                pass        # corrupt snapshot: rejoin cold, not crash
        return h

    # ------------------------------------------------------------------ #
    # reporting
    # ------------------------------------------------------------------ #

    def request_records(self) -> List[Union[Completion,
                                            ShedCompletion]]:
        """Every delivered fleet-level terminal record, in delivery
        order — each fleet id exactly once (the idempotent-delivery
        contract), with fleet-honest ``t_submit`` whatever replica
        served it."""
        return list(self._records)

    def stats(self) -> dict:
        return {
            "placement": self.placement,
            "steps": self.step_count,
            "replicas": {h.name: h.snapshot() for h in self.replicas},
            "n_healthy": self.n_healthy,
            "inflight": len(self._flights),
            "pending": len(self._pending),
            "delivered": len(self._delivered),
            "failovers": self.n_failovers,
            "migrated": self.n_migrated,
            "hedges": self.n_hedges,
            "hedge_won": self.n_hedge_won,
            "hedge_lost": self.n_hedge_lost,
            "retries": self.n_retries,
            "sheds": self.n_sheds,
            "retry_budget": self.retry_budget.snapshot(),
            "predicted_queue_wait": self.predicted_queue_wait(),
        }

    def status(self) -> dict:
        """The statusz section form (``server.add_section("fleet",
        router)`` binds this)."""
        return self.stats()
