"""Serving subsystem: slot-based continuous batching over a
block-paged KV cache, with SLO-driven admission control and a
production decode tier (prefix sharing, keyed sampling, speculative
decoding).

``engine`` schedules requests onto decode slots (queue, admission into
freed slots mid-stream, RAGGED per-row position clocks — every row
advances on its own origin-0 lane, chunked prefill interleaves into
decode rounds, speculation is a round mode — per-row EOS eviction,
FCFS/shortest-prompt/deadline/WFQ policies, per-request deadlines +
``cancel()``, decode-round quarantine); ``admission`` supplies the
overload layer (split wait/service-time prediction from the live
TTFT/TPOT lattice histograms, bounded queue with priority
displacement, per-tenant token quotas with deficit-round-robin WFQ
scheduling, reason-coded ``ShedCompletion`` fast rejects);
``kv_blocks`` supplies the paging layer (free-list block allocator,
chunked prefill-to-pool scatter, copy-on-admit gather) that keeps the
decode step one compiled program over the dense static cache;
``prefix_cache`` adds copy-on-write prefix sharing over it (refcounted
blocks, a prefix trie keyed by token-id chunks — N requests sharing a
system prompt hold ONE physical copy and stage only their divergent
suffix, with mid-block divergence forking the matched sub-block
prefix by device copy); ``sampling`` threads per-request keyed
temperature/top-k/top-p streams through the decode round (greedy
stays the byte-identical exactness oracle, sampled runs pin by keyed
replay); ``speculative`` drafts k tokens with a cheap adapter and
verifies them in one target pass (greedy output exactly the
target-only decode) as a standalone/offline tier — in-engine, pass
``draft_adapter=`` and the engine runs per-row speculative ROUNDS;
``slo`` scores request records (percentiles
+ SLO attainment/goodput + extra columns like acceptance/hit rates);
``minilm`` is the portable reference decode backend (and
adapter-protocol example) — the flagship transformer rides the same
engine through :class:`TransformerAdapter`; ``fleet`` fronts N engine
replicas with prefix-cache-aware routing, replica health/failover
(queue migration + committed-prefix re-dispatch, exactly-once
delivery), budgeted retries with hedged dispatch, and brown-out
degradation.  See docs/SERVING.md ("Serving at scale", "Overload and
admission", "Prefix sharing", "Sampling", "Speculative serving",
"Fleet"), ``bench_serving.py``, ``bench_overload.py`` and
``bench_fleet.py``.
"""

from .admission import (
    SHED_REASONS,
    AdmissionController,
    ServiceTimePredictor,
    ShedCompletion,
)
from .engine import Completion, Request, ServingEngine, TransformerAdapter
from .fleet import FleetRouter, ReplicaHandle, RetryBudget
from .kv_blocks import BlockAllocator, blocks_needed
from .minilm import MiniLMAdapter, MiniLMConfig, init_minilm
from .prefix_cache import (
    PrefixTrie,
    RefcountedBlockPool,
    StagePlan,
    load_prefix_snapshot,
    prefix_snapshot,
)
from .sampling import SamplingParams
from .slo import SLOReport
from .speculative import SpecResult, SpeculativeDecoder

__all__ = [
    "AdmissionController",
    "BlockAllocator",
    "Completion",
    "FleetRouter",
    "MiniLMAdapter",
    "MiniLMConfig",
    "PrefixTrie",
    "RefcountedBlockPool",
    "ReplicaHandle",
    "Request",
    "RetryBudget",
    "SHED_REASONS",
    "SLOReport",
    "SamplingParams",
    "ServiceTimePredictor",
    "ServingEngine",
    "ShedCompletion",
    "SpecResult",
    "SpeculativeDecoder",
    "StagePlan",
    "TransformerAdapter",
    "blocks_needed",
    "init_minilm",
    "load_prefix_snapshot",
    "prefix_snapshot",
]
