"""Serving subsystem: slot-based continuous batching over a
block-paged KV cache.

``engine`` schedules requests onto decode slots (queue, admission into
freed slots mid-stream, per-row EOS eviction, FCFS/shortest-prompt
policies); ``kv_blocks`` supplies the paging layer (free-list block
allocator, prefill-to-pool scatter, copy-on-admit gather, horizon
rebase) that keeps the decode step one compiled program over the dense
static cache; ``minilm`` is the portable reference decode backend (and
adapter-protocol example) — the flagship transformer rides the same
engine through :class:`TransformerAdapter`.  See docs/SERVING.md
("Serving at scale") and ``bench_serving.py``.
"""

from .engine import Completion, Request, ServingEngine, TransformerAdapter
from .kv_blocks import BlockAllocator, blocks_needed
from .minilm import MiniLMAdapter, MiniLMConfig, init_minilm
from .slo import SLOReport

__all__ = [
    "BlockAllocator",
    "Completion",
    "MiniLMAdapter",
    "MiniLMConfig",
    "Request",
    "SLOReport",
    "ServingEngine",
    "TransformerAdapter",
    "blocks_needed",
    "init_minilm",
]
