"""Serving subsystem: slot-based continuous batching over a
block-paged KV cache, with SLO-driven admission control.

``engine`` schedules requests onto decode slots (queue, admission into
freed slots mid-stream, per-row EOS eviction, FCFS/shortest-prompt/
deadline policies, per-request deadlines + ``cancel()``, decode-round
quarantine); ``admission`` supplies the overload layer (service-time
prediction from the live TTFT/TPOT lattice histograms, bounded queue
with priority displacement, per-tenant token quotas, reason-coded
``ShedCompletion`` fast rejects); ``kv_blocks`` supplies the paging
layer (free-list block allocator, prefill-to-pool scatter,
copy-on-admit gather, horizon rebase) that keeps the decode step one
compiled program over the dense static cache; ``slo`` scores request
records (percentiles + SLO attainment/goodput); ``minilm`` is the
portable reference decode backend (and adapter-protocol example) —
the flagship transformer rides the same engine through
:class:`TransformerAdapter`.  See docs/SERVING.md ("Serving at
scale", "Overload and admission"), ``bench_serving.py`` and
``bench_overload.py``.
"""

from .admission import (
    SHED_REASONS,
    AdmissionController,
    ServiceTimePredictor,
    ShedCompletion,
)
from .engine import Completion, Request, ServingEngine, TransformerAdapter
from .kv_blocks import BlockAllocator, blocks_needed
from .minilm import MiniLMAdapter, MiniLMConfig, init_minilm
from .slo import SLOReport

__all__ = [
    "AdmissionController",
    "BlockAllocator",
    "Completion",
    "MiniLMAdapter",
    "MiniLMConfig",
    "Request",
    "SHED_REASONS",
    "SLOReport",
    "ServiceTimePredictor",
    "ServingEngine",
    "ShedCompletion",
    "TransformerAdapter",
    "blocks_needed",
    "init_minilm",
]
