"""Speculative draft/verify decoding over the serving adapter protocol.

Decode is HBM-bound: one read of the target's weights per token.  A
cheap DRAFT model proposes ``k`` tokens per round and the target
verifies the whole chunk in ONE pass (``adapter.verify`` — one weights
read for up to ``k + 1`` committed tokens), so tokens/sec multiplies
by roughly the mean accepted length.  ``models.decoding`` already
ships this for the flagship transformer as a single fused program;
this module is the SERVING-TIER sibling, built on the engine's
decode-adapter protocol instead of ``TransformerConfig`` internals:

- **Any adapter pair.**  Drafter and target are two decode adapters
  (``make_cache`` / ``prefill`` / ``step`` / ``verify``).  Two MiniLM
  configs make the whole subsystem runnable pre-vma — the parity
  suite's oracle world — while
  :class:`~chainermn_tpu.serving.engine.TransformerAdapter` carries
  the same ``verify`` surface for the flagship (vma-marked, like
  every ``TransformerConfig`` path).
- **Exactness ladder.**  Greedy target ⇒ the output is exactly the
  target-only greedy decode: only verified argmax matches commit, and
  the corrective/bonus token is the target's own argmax (the
  ``_verify_and_commit`` contract, re-pinned here per adapter).
  Sampled target (``sampling=``) runs the standard Leviathan/Chen
  reject/resample: each proposal accepts with probability
  ``min(1, p_t'/p_d')`` on the temperature/top-k/top-p-filtered pair,
  a rejection draws from the residual ``max(0, p_t' − p_d')``, a
  fully-accepted round draws the bonus from ``p_t'`` — and the whole
  run replays bit-identically from ``(seed, params, prompt)``
  (:mod:`~chainermn_tpu.serving.sampling` key-stream discipline).
- **Observability.**  ``serve/spec_drafted`` / ``serve/spec_accepted``
  count every proposal and acceptance (their ratio IS the speedup
  lever); each round emits ``serve/draft`` and ``serve/verify``
  spans.

Host-driven rounds over jitted draft/verify programs, single request
per call — the standalone/offline tier.  For continuous serving, pass
``draft_adapter=`` to :class:`~chainermn_tpu.serving.engine.ServingEngine`
and the engine runs speculation as a ROUND MODE over its ragged
per-row position clocks (per-row acceptance, same counters); the
fused batch form lives in ``models.decoding``.  See docs/SERVING.md
"Speculative serving" and "Ragged rounds".
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.utils.metrics import get_registry
from chainermn_tpu.utils.telemetry import get_recorder

from .sampling import SamplingParams, filter_logits

__all__ = ["SpecResult", "SpeculativeDecoder"]


@dataclasses.dataclass(eq=False)
class SpecResult:
    """One speculative generation: ``tokens`` are the generated tokens
    (first EOS kept, budget-truncated — the ``make_generate_fn``
    convention); the counters quantify the draft's worth (each round
    costs one draft k-step pass plus ONE target pass and commits
    ``1..k+1`` tokens)."""

    tokens: np.ndarray
    rounds: int
    drafted: int
    accepted: int

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def tokens_per_round(self) -> float:
        return (int(self.tokens.shape[0]) / self.rounds
                if self.rounds else 0.0)


class SpeculativeDecoder:
    """Draft-k / verify-in-one-pass decoding over two decode adapters.

    Args:
      draft_adapter / draft_params: the cheap proposer (e.g. a small
        :class:`~chainermn_tpu.serving.minilm.MiniLMAdapter`).
      target_adapter / target_params: the model whose decode the
        output must reproduce.  Both adapters must expose ``verify``
        (chunk step with logits) in addition to the engine protocol.
      k: proposals per round.
      max_prompt / horizon: prompt capacity and cache length —
        prompts right-align into a fixed ``max_prompt`` window (one
        compiled prefill, the engine convention) and the cache holds
        ``horizon + k + 1`` positions (rounds may overshoot by a
        chunk).
      eos_id / pad_id: early-stop semantics, exactly
        ``make_generate_fn``'s.

    Single-request calls on plain (unsharded) arrays: the adapters'
    pure functions are used directly under ``jit``, so the decoder
    runs on any jax — no mesh, no vma requirement beyond what the
    adapters themselves impose.
    """

    def __init__(self, draft_adapter, draft_params, target_adapter,
                 target_params, *, k: int = 4, max_prompt: int,
                 horizon: int, eos_id: int = -1, pad_id: int = 0):
        if k < 1:
            raise ValueError(f"k={k} must be >= 1")
        if max_prompt < 1 or horizon <= max_prompt:
            raise ValueError(
                f"need max_prompt >= 1 < horizon, got {max_prompt} / "
                f"{horizon}")
        dv = getattr(getattr(draft_adapter, "cfg", None),
                     "vocab_size", None)
        tv = getattr(getattr(target_adapter, "cfg", None),
                     "vocab_size", None)
        if dv is not None and tv is not None and dv != tv:
            raise ValueError(f"draft vocab {dv} != target vocab {tv}")
        self.draft = draft_adapter
        self.d_params = draft_params
        self.target = target_adapter
        self.t_params = target_params
        self.k = int(k)
        self.max_prompt = int(max_prompt)
        self.horizon = int(horizon)
        self.eos_id = int(eos_id)
        self.pad_id = int(pad_id)
        self._jits = {}

    # -- jitted programs (cached per shape) ---------------------------- #

    def _jit(self, name, fn):
        if name not in self._jits:
            from chainermn_tpu.utils.programs import ledger_jit

            # ledger label: the program kind only — the adapter id in
            # a ("prefill", id) key is cache identity, not a label
            kind = name[0] if isinstance(name, tuple) else name
            self._jits[name] = ledger_jit(fn, label=f"spec/{kind}")
        return self._jits[name]

    def mark_steady(self) -> None:
        """Declare this decoder's ``spec/*`` programs steady-state in
        the program ledger (the ``ServingEngine.mark_steady``
        twin — the engine's ``serve/`` scope does NOT cover these):
        call after warmup generations have compiled the draft/verify
        programs for the splits you serve, and any further ``spec/``
        compile counts as ``compile/steady_retraces`` — the
        speculative half of the retrace-storm coverage.  A rebuild
        (new adapters) should ``get_ledger().forget("spec/")``,
        re-warm, re-mark."""
        from chainermn_tpu.utils.programs import get_ledger

        get_ledger().mark_steady("spec/")

    def _prefill(self, ad, params, kv_len, row, offs):
        def body(params, row, offs):
            caches = ad.make_cache(1, kv_len)
            return ad.prefill(params, caches, row[:, :-1], offs)

        return self._jit(("prefill", id(ad)), body)(params, row, offs)

    def _draft_round(self, d_cache, cur, pos, offs):
        """k greedy proposals + the trailing cache-fill step (a
        fully-accepted round must not leave a K/V hole at the last
        proposal's position — the ``models.decoding`` lesson)."""
        def body(params, d_cache, cur, pos, offs):
            props = []
            for j in range(self.k):
                logits, d_cache = self.draft.step(
                    params, d_cache, cur, pos + j, offs)
                cur = jnp.argmax(logits, -1).astype(jnp.int32)
                props.append(cur)
            _, d_cache = self.draft.verify(
                params, d_cache, cur[:, None], pos + self.k, offs,
                with_logits=False)
            return jnp.stack(props, 1), d_cache

        return self._jit("draft", body)(self.d_params, d_cache, cur,
                                        pos, offs)

    def _draft_round_sampled(self, d_cache, cur, pos, offs, keys,
                             temp, top_k, top_p):
        """k SAMPLED proposals with their filtered log-probs p_d′ —
        the draft side of the Leviathan/Chen pair."""
        def body(params, d_cache, cur, pos, offs, keys, temp, top_k,
                 top_p):
            props, lps = [], []
            for j in range(self.k):
                logits, d_cache = self.draft.step(
                    params, d_cache, cur, pos + j, offs)
                lp = jax.nn.log_softmax(filter_logits(
                    logits.astype(jnp.float32) / temp, top_k, top_p),
                    -1)
                cur = jax.random.categorical(keys[j], lp) \
                    .astype(jnp.int32)
                props.append(cur)
                lps.append(lp[0])
            _, d_cache = self.draft.verify(
                params, d_cache, cur[:, None], pos + self.k, offs,
                with_logits=False)
            return jnp.stack(props, 1), jnp.stack(lps, 0), d_cache

        return self._jit("draft_sampled", body)(
            self.d_params, d_cache, cur, pos, offs, keys, temp, top_k,
            top_p)

    def _verify(self, t_cache, chunk, pos, offs):
        def body(params, t_cache, chunk, pos, offs):
            return self.target.verify(params, t_cache, chunk, pos,
                                      offs)

        return self._jit("verify", body)(self.t_params, t_cache, chunk,
                                         pos, offs)

    def _target_step(self, t_cache, cur, pos, offs):
        def body(params, t_cache, cur, pos, offs):
            logits, t_cache = self.target.step(params, t_cache, cur,
                                               pos, offs)
            return jnp.argmax(logits, -1).astype(jnp.int32), t_cache

        return self._jit("tstep", body)(self.t_params, t_cache, cur,
                                        pos, offs)

    # -- public API ---------------------------------------------------- #

    def _layout(self, prompt):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not 1 <= prompt.shape[0] <= self.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} not in "
                f"[1, {self.max_prompt}]")
        row = np.full((1, self.max_prompt), max(self.pad_id, 0),
                      np.int32)
        row[0, self.max_prompt - prompt.shape[0]:] = prompt
        offs = jnp.asarray(
            [self.max_prompt - prompt.shape[0]], jnp.int32)
        return prompt, jnp.asarray(row), offs

    def _finish(self, out, rounds, drafted, accepted):
        toks = np.asarray(out, np.int32)
        if self.eos_id >= 0:
            hits = np.nonzero(toks == self.eos_id)[0]
            if hits.size:
                toks = toks[:int(hits[0]) + 1]
        reg = get_registry()
        reg.inc("serve/spec_drafted", drafted)
        reg.inc("serve/spec_accepted", accepted)
        return SpecResult(tokens=toks, rounds=rounds, drafted=drafted,
                          accepted=accepted)

    def target_decode(self, prompt, max_new: int) -> np.ndarray:
        """The target-only greedy decode (same layout, no draft) —
        the baseline a speculative run is measured against and the
        reference its greedy output must EQUAL."""
        prompt, row, offs = self._layout(prompt)
        kv = self.horizon + self.k + 1
        t_cache = self._prefill(self.target, self.t_params, kv, row,
                                offs)
        cur = jnp.asarray(prompt[-1:], jnp.int32)
        out = []
        pos = self.max_prompt - 1
        for _ in range(max_new):
            cur, t_cache = self._target_step(t_cache, cur,
                                             jnp.int32(pos), offs)
            out.append(int(cur[0]))
            pos += 1
            if self.eos_id >= 0 and out[-1] == self.eos_id:
                break
        return np.asarray(out, np.int32)

    def generate(self, prompt, max_new: int,
                 sampling: Optional[SamplingParams] = None
                 ) -> SpecResult:
        """Speculatively decode ``max_new`` tokens (fewer on EOS).
        Greedy without ``sampling``; with it, the draft proposes from
        its filtered distribution and the Leviathan/Chen test keeps
        the output distribution exactly the target's."""
        if max_new < 1:
            raise ValueError(f"max_new={max_new} must be >= 1")
        if self.max_prompt + max_new > self.horizon:
            raise ValueError(
                f"max_new={max_new} exceeds horizon - max_prompt = "
                f"{self.horizon - self.max_prompt}")
        prompt, row, offs = self._layout(prompt)
        kv = self.horizon + self.k + 1
        rec = get_recorder()
        t_cache = self._prefill(self.target, self.t_params, kv, row,
                                offs)
        d_cache = self._prefill(self.draft, self.d_params, kv, row,
                                offs)
        cur = jnp.asarray(prompt[-1:], jnp.int32)
        pos = self.max_prompt - 1
        out = []
        rounds = drafted = accepted = 0
        if sampling is not None:
            temp = jnp.float32(sampling.temperature)
            s_topk = jnp.int32(sampling.top_k)
            s_topp = jnp.float32(sampling.top_p)
            root = sampling.key()
        while len(out) < max_new:
            rounds += 1
            with rec.span("serve/draft", cat="serve", k=self.k,
                          step=pos):
                if sampling is None:
                    props, d_cache = self._draft_round(
                        d_cache, cur, jnp.int32(pos), offs)
                    d_lp = None
                else:
                    # the round's key fan: k draft draws + the
                    # accept/residual draws, all folded from the
                    # ROUND-START token index — schedule-free replay
                    rk = jax.random.fold_in(root, len(out))
                    dkeys = jax.random.split(rk, self.k + 2)
                    props, d_lp, d_cache = self._draft_round_sampled(
                        d_cache, cur, jnp.int32(pos), offs,
                        dkeys[:self.k], temp, s_topk, s_topp)
            chunk = jnp.concatenate([cur[:, None], props], axis=1)
            with rec.span("serve/verify", cat="serve", k=self.k,
                          step=pos):
                tlog, t_cache = self._verify(t_cache, chunk,
                                             jnp.int32(pos), offs)
            props_np = np.asarray(props[0])
            drafted += self.k
            if sampling is None:
                g = np.asarray(jnp.argmax(tlog[0], -1))    # (k+1,)
                n_acc = 0
                while n_acc < self.k and props_np[n_acc] == g[n_acc]:
                    n_acc += 1
                commit = list(props_np[:n_acc]) + [int(g[n_acc])]
            else:
                t_lp = jax.nn.log_softmax(filter_logits(
                    tlog[0].astype(jnp.float32) / temp, s_topk,
                    s_topp), -1)                           # (k+1, V)
                u = jax.random.uniform(dkeys[self.k], (self.k,),
                                       minval=1e-20)
                t_at = np.asarray(jnp.take_along_axis(
                    t_lp[:self.k], jnp.asarray(props_np)[:, None],
                    1)[:, 0])
                d_at = np.asarray(jnp.take_along_axis(
                    d_lp, jnp.asarray(props_np)[:, None], 1)[:, 0])
                acc = np.asarray(jnp.log(u)) < (t_at - d_at)
                n_acc = 0
                while n_acc < self.k and acc[n_acc]:
                    n_acc += 1
                t_p = jnp.exp(t_lp[n_acc])
                if n_acc < self.k:
                    # rejected at the cut: residual max(0, p_t′−p_d′)
                    d_p = jnp.exp(d_lp[n_acc])
                    resid = jnp.maximum(t_p - d_p, 0.0)
                    rs = resid.sum()
                    dist = jnp.where(rs > 1e-9, resid / rs, t_p)
                else:
                    dist = t_p                  # bonus draw from p_t′
                tok = int(jax.random.categorical(
                    dkeys[self.k + 1],
                    jnp.log(jnp.maximum(dist, 1e-30))))
                commit = list(props_np[:n_acc]) + [tok]
            accepted += n_acc
            # land the committed tokens; stale K/V beyond the cut is
            # overwritten by the next round's chunk before any query
            # can attend it (both caches cover [pos, pos+k])
            out.extend(int(t) for t in commit)
            cur = jnp.asarray([out[-1]], jnp.int32)
            pos += n_acc + 1
            if self.eos_id >= 0 \
                    and any(t == self.eos_id for t in commit):
                break
        return self._finish(out[:max_new], rounds, drafted, accepted)
