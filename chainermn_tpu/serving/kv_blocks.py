"""Block-paged KV cache primitives for the serving engine.

The decode stack keeps its KV cache as ONE dense static-shape buffer
per component (``models.decoding._make_cache``: ``(L, rows, kv_len,
heads, d_head)``) so the per-token step stays a single compiled
program.  Continuous batching breaks the assumption behind that shape:
requests arrive and finish raggedly, so neither the row set nor the
position range is fixed for the lifetime of the program.  This module
supplies the paging layer that reconciles the two:

- :class:`BlockAllocator` — a host-side free-list allocator over
  fixed-size POSITION blocks with per-row block tables.  A staged
  (prefilled but not yet scheduled) request holds ``ceil(P/block)``
  blocks — its actual prompt footprint — instead of a whole
  ``max_len`` slot, which is how heterogeneous prompt lengths share
  the staging pool.
- Device-side block ops (:func:`chunk_to_blocks`,
  :func:`scatter_chunk`, :func:`gather_blocks`, :func:`insert_chunk`,
  :func:`shift_positions`) — pure ``jnp`` functions over cache
  COMPONENT arrays, composable inside any ``shard_map`` body.  The
  engine strings them into its jitted programs: chunked prefill→pool
  (gather + scatter per chunk), pool→slot copy-on-admit (gather +
  contiguous insert — the defrag step that lets the decode program
  keep reading a dense per-slot layout), and the copy-on-write block
  fork (:func:`copy_block`).  Rows decode origin-0 against their own
  per-row position clocks, so a lane never shifts; a row's positions
  simply end at ``prompt + max_new - 1 <= horizon - 1``
  (:func:`shift_positions` remains for callers that relocate lane
  content wholesale).

Layout convention (shared with ``_make_cache``): every cache component
carries its ROWS on axis 1 and its POSITIONS on axis 2; leading axis 0
(layers) and trailing axes (heads, head dim, int8-scale singletons)
are opaque.  The pool form of a component replaces (rows, positions)
with (n_blocks, block): physically scattered fixed-size position
blocks, addressed only through per-row tables — exactly the
memory-efficient redistribution framing of PAPERS.md 2112.01075, with
the gather/scatter pair as the portable collective-free lowering.

Trade-off, stated plainly: true paged ATTENTION (vLLM-style) indexes
the block table inside the kernel and never copies; this layer instead
pays one O(prompt) copy per admission so the hot per-token step stays
byte-for-byte the program ``_make_cache`` already compiles.  On a step that reads the whole
cache every token anyway, the admission copy is noise; what paging
buys here is the ragged-length pool accounting and the static-shape
guarantee.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "BlockAllocator",
    "ROW_AXIS",
    "POS_AXIS",
    "blocks_needed",
    "chunk_to_blocks",
    "scatter_chunk",
    "gather_blocks",
    "gather_positions",
    "copy_block",
    "insert_chunk",
    "shift_positions",
]

# Cache-component layout contract (see module docstring).
ROW_AXIS = 1
POS_AXIS = 2


def blocks_needed(length: int, block: int) -> int:
    """Blocks covering ``length`` positions (0 positions → 0 blocks)."""
    if length < 0:
        raise ValueError(f"length {length} must be >= 0")
    return -(-length // block)


class BlockAllocator:
    """Free-list allocator over a pool of fixed-size position blocks.

    Host-side bookkeeping only — the device arrays live with the
    engine.  Rows (request ids) own lists of physical block ids; the
    free list is LIFO so recently-freed blocks are reused while still
    warm.  Allocation is all-or-nothing: a request that cannot get its
    full block count holds nothing (no partial admissions to unwind).
    """

    def __init__(self, n_blocks: int, block: int):
        if n_blocks < 1 or block < 1:
            raise ValueError(
                f"n_blocks={n_blocks} and block={block} must be >= 1")
        self.n_blocks = int(n_blocks)
        self.block = int(block)
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._tables: Dict[object, List[int]] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def utilization(self) -> float:
        """Fraction of pool blocks currently owned by rows."""
        return 1.0 - len(self._free) / self.n_blocks

    def rows(self):
        return list(self._tables)

    def table(self, row_id) -> List[int]:
        """The row's block ids, oldest position first (a copy)."""
        return list(self._tables[row_id])

    def alloc(self, row_id, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks for ``row_id``; ``None`` if the pool
        cannot satisfy the FULL request (nothing is taken)."""
        if row_id in self._tables:
            raise ValueError(f"row {row_id!r} already holds blocks")
        if n < 0:
            raise ValueError(f"n={n} must be >= 0")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._tables[row_id] = ids
        return list(ids)

    def free_row(self, row_id) -> int:
        """Return the row's blocks to the free list; count returned.
        Unknown rows free nothing (idempotent evictions)."""
        ids = self._tables.pop(row_id, None)
        if not ids:
            return 0
        self._free.extend(reversed(ids))
        return len(ids)

    def padded_table(self, row_id, width: int) -> np.ndarray:
        """The row's table RIGHT-aligned into ``width`` int32 entries,
        missing leading entries = -1.  This is the wire form the
        engine's admit program takes: a right-aligned prompt occupies
        the LAST ``len(table)`` of its padded chunk's blocks, so the
        -1 padding marks the chunk blocks that hold only left-pad
        garbage (gathered from a clamped id and masked by the
        attention validity window — never read as real K/V)."""
        ids = self._tables[row_id]
        if len(ids) > width:
            raise ValueError(
                f"row {row_id!r} holds {len(ids)} blocks > width {width}")
        out = np.full((width,), -1, np.int32)
        if ids:
            out[width - len(ids):] = np.asarray(ids, np.int32)
        return out


# ---------------------------------------------------------------------- #
# device-side block ops (pure jnp; usable inside shard_map bodies)
# ---------------------------------------------------------------------- #

def chunk_to_blocks(comp, block: int):
    """Reshape a one-row cache component ``(..., 1, Pq, *rest)`` into
    its block form ``(..., Pq // block, block, *rest)``."""
    import jax.numpy as jnp  # noqa: F401  (kept light at module import)

    if comp.shape[ROW_AXIS] != 1:
        raise ValueError(
            f"chunk must hold one row, got {comp.shape[ROW_AXIS]}")
    pq = comp.shape[POS_AXIS]
    if pq % block:
        raise ValueError(f"chunk positions {pq} not divisible by "
                         f"block {block}")
    shape = (comp.shape[0], pq // block, block) + comp.shape[3:]
    return comp.reshape(shape)


def scatter_chunk(pool_comp, block_comp, ids, valid):
    """Write a chunk's blocks into the pool at physical ``ids``.

    ``pool_comp``: ``(D0, n_blocks, block, *rest)``; ``block_comp``:
    ``(D0, W, block, *rest)``; ``ids``: (W,) int32 (invalid entries
    may be any value); ``valid``: (W,) bool.  Invalid entries are
    routed OUT of bounds and dropped (``mode="drop"``) — clamping
    them to a real block would collide with that block's own write
    whenever the allocator legitimately hands it out, and scatter
    order for duplicate indices is backend-defined."""
    import jax.numpy as jnp

    nb = pool_comp.shape[1]
    idx = jnp.where(valid, jnp.clip(ids, 0, nb - 1), nb)
    return pool_comp.at[:, idx].set(block_comp, mode="drop")


def gather_blocks(pool_comp, ids):
    """Assemble pool blocks ``ids`` (W,) into a contiguous one-row
    chunk ``(D0, 1, W * block, *rest)``.  Ids are clamped — invalid
    (-1) entries produce garbage positions whose content the caller
    must keep outside every attention validity window (the engine's
    left-pad region)."""
    import jax.numpy as jnp

    nb = pool_comp.shape[1]
    idx = jnp.clip(ids, 0, nb - 1)
    picked = jnp.take(pool_comp, idx, axis=1)   # (D0, W, block, *rest)
    shape = (picked.shape[0], 1, picked.shape[1] * picked.shape[2]) \
        + picked.shape[3:]
    return picked.reshape(shape)


def gather_positions(pool_comp, flat_idx):
    """Assemble individual pool POSITIONS into a contiguous one-row
    chunk ``(D0, 1, Pq, *rest)``.  ``flat_idx`` (Pq,) int32 addresses
    ``block_id * block + intra`` over the flattened pool; entries are
    clamped, so invalid (-1) entries produce garbage positions the
    caller must keep outside every attention validity window.

    This is the position-granular sibling of :func:`gather_blocks` —
    the prefix-sharing admit path uses it because a LEFT-aligned
    staged prompt lands RIGHT-aligned in its slot lane: the shift
    between the two layouts is sub-block whenever the prompt length is
    not a block multiple, which a block-granular gather cannot
    express."""
    import jax.numpy as jnp

    nb, blk = pool_comp.shape[1], pool_comp.shape[2]
    flat = pool_comp.reshape((pool_comp.shape[0], nb * blk)
                             + pool_comp.shape[3:])
    idx = jnp.clip(flat_idx, 0, nb * blk - 1)
    picked = jnp.take(flat, idx, axis=1)        # (D0, Pq, *rest)
    return picked.reshape((picked.shape[0], 1, picked.shape[1])
                          + picked.shape[2:])


def copy_block(pool_comp, src, dst, ok):
    """Copy-on-write fork: duplicate physical block ``src`` into
    ``dst`` (scalars; ``ok`` gates the write like
    :func:`insert_chunk`).  The fork is how a row gains a PRIVATE copy
    of a block it currently shares — the shared original is never
    written through."""
    from jax import lax
    import jax.numpy as jnp

    blk = lax.dynamic_slice_in_dim(pool_comp, src, 1, axis=1)
    cur = lax.dynamic_slice_in_dim(pool_comp, dst, 1, axis=1)
    new = jnp.where(ok, blk, cur)
    return lax.dynamic_update_slice_in_dim(pool_comp, new, dst, axis=1)


def insert_chunk(cache_comp, chunk_comp, row, dst, ok):
    """Copy-on-admit: land a contiguous chunk ``(D0, 1, Pq, *rest)``
    into ``cache_comp`` at (local) ``row``, positions ``[dst, dst+Pq)``.
    ``ok`` (scalar bool) gates the write — on a row-sharded cache only
    the shard owning the global slot writes, everyone else rewrites
    the current value (``row`` must arrive pre-clamped into local
    range)."""
    import jax.numpy as jnp
    from jax import lax

    start = (0, row, dst) + (0,) * (cache_comp.ndim - 3)
    cur = lax.dynamic_slice(cache_comp, start, chunk_comp.shape)
    new = jnp.where(ok, chunk_comp, cur)
    return lax.dynamic_update_slice(cache_comp, new, start)


def shift_positions(comp, delta):
    """Shift a component's position axis down by ``delta``
    (``new[..., p, ...] = old[..., p + delta, ...]``, tail clamped to
    the last position).  Historically the horizon-rebase primitive;
    the ragged engine's origin-0 per-row clocks never shift a lane,
    but the op stays exported for callers that relocate lane content
    wholesale (a caller must keep the clamped tail outside every
    attention window until rewritten)."""
    import jax.numpy as jnp

    h = comp.shape[POS_AXIS]
    idx = jnp.clip(jnp.arange(h) + delta, 0, h - 1)
    return jnp.take(comp, idx, axis=POS_AXIS)
