"""MiniLM — the serving engine's reference decode backend.

A compact MQA causal LM (pre-LN residual blocks, learned positions,
one shared KV head) whose step/prefill functions follow the engine's
decode-adapter protocol.  It exists for two reasons:

- **Portability.**  The flagship transformer deliberately refuses to
  construct on pre-vma jax (its training VJPs need varying-axes
  typing), which means every engine test and the serving bench would
  be dead on the jaxes this repo still supports.  MiniLM is written
  with plain ``jnp`` — no vma typing, no custom VJPs, no axis-name
  queries — so the engine has a live backend (and the parity suite a
  runnable oracle) everywhere.  The flagship path rides the same
  engine through :class:`~chainermn_tpu.serving.TransformerAdapter`.
- **Protocol example.**  The adapter surface is exactly what a decode
  backend owes the engine: ``make_cache``/``prefill``/``step`` with
  the per-row position-origin (``pos_offset``) contract, plus the
  sharding specs the engine's programs cross the jit boundary with.

Position/masking contract (shared with ``models.decoding``): a row
whose origin is ``offset`` holds its token number ``i`` at buffer/cache
position ``offset + i``; queries may only attend cache positions in
``[offset, t]``; learned-position rows index the table at
``position - offset``.  All methods are pure and equally callable
inside a ``shard_map`` body (the engine) or on plain arrays (the
tests' independent oracle).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .kv_blocks import POS_AXIS

__all__ = ["MiniLMConfig", "init_minilm", "MiniLMAdapter"]

_NEG = -1e30   # finite attention mask (same convention as ring_attention)


@dataclasses.dataclass(frozen=True)
class MiniLMConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_head: int = 16
    d_ff: int = 128
    n_layers: int = 2
    max_pos: int = 512     # learned position table length (>= P + N)

    def __post_init__(self):
        if min(self.vocab_size, self.d_model, self.n_heads, self.d_head,
               self.d_ff, self.n_layers, self.max_pos) < 1:
            raise ValueError(f"all MiniLMConfig sizes must be >= 1: {self}")


def init_minilm(key, cfg: MiniLMConfig):
    """Random fp32 parameters; per-layer leaves stacked on axis 0."""
    k = jax.random.split(key, 8)
    d, hq, dh, f, layers = (cfg.d_model, cfg.n_heads, cfg.d_head,
                            cfg.d_ff, cfg.n_layers)

    def w(key, *shape):
        return jax.random.normal(key, shape, jnp.float32) \
            / np.sqrt(shape[-2] if len(shape) > 1 else 1.0)

    return {
        "embed": w(k[0], cfg.vocab_size, d) * np.sqrt(d),
        "pos": w(k[1], cfg.max_pos, d) * 0.1,
        "ln_f": jnp.ones((d,), jnp.float32),
        "blocks": {
            "ln1": jnp.ones((layers, d), jnp.float32),
            "wq": w(k[2], layers, d, hq * dh),
            "wk": w(k[3], layers, d, dh),
            "wv": w(k[4], layers, d, dh),
            "wo": w(k[5], layers, hq * dh, d),
            "ln2": jnp.ones((layers, d), jnp.float32),
            "w1": w(k[6], layers, d, f),
            "w2": w(k[7], layers, f, d),
        },
    }


def _rms(x, g):
    return x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * g


class MiniLMAdapter:
    """Decode-adapter protocol implementation for :func:`init_minilm`
    parameters.  Parameters ride replicated (``P()``); the cache and
    every per-slot array shard over the batch axes.  The mesh may
    carry model/pipe/seq axes only at size 1 (MiniLM does not split
    its own math)."""

    batch_axes = ("data", "expert")

    def __init__(self, mesh_cfg, cfg: MiniLMConfig):
        shape = mesh_cfg.mesh.shape
        bad = {a: shape[a] for a in ("model", "pipe", "seq")
               if shape.get(a, 1) != 1}
        if bad:
            raise ValueError(
                f"MiniLMAdapter shards only the batch axes "
                f"{self.batch_axes}; mesh has non-unit axes {bad}")
        self.mesh_cfg = mesh_cfg
        self.cfg = cfg

    # -- sharding surface ------------------------------------------------ #

    def param_specs(self):
        return P()     # pytree prefix: every leaf replicated

    def cache_specs(self):
        bs = P(None, self.batch_axes)   # (L, rows, kv_len, d_head)
        return (bs, bs)

    # -- cache ----------------------------------------------------------- #

    def make_cache(self, rows: int, kv_len: int, batch_varying=True):
        """Zero MQA cache pair ``(L, rows, kv_len, d_head)`` (local
        shapes; rows axis 1, positions axis 2 — the kv_blocks layout
        contract).  ``batch_varying`` exists for protocol parity with
        the transformer adapter (MiniLM carries no vma types)."""
        del batch_varying
        shape = (self.cfg.n_layers, rows, kv_len, self.cfg.d_head)
        return (jnp.zeros(shape, jnp.float32),
                jnp.zeros(shape, jnp.float32))

    # -- forward --------------------------------------------------------- #

    def _positions(self, params, idx):
        return jnp.take(params["pos"],
                        jnp.clip(idx, 0, self.cfg.max_pos - 1), axis=0)

    def step(self, params, caches, tok, t, pos_offset):
        """One token for every row: ``tok`` (B,) int32 at global
        position ``t`` (scalar), per-row origins ``pos_offset`` (B,).
        Returns ``(logits (B, V) fp32, caches)``."""
        cfg = self.cfg
        ck, cv = caches
        B = tok.shape[0]
        T = ck.shape[POS_AXIS]
        h = jnp.take(params["embed"], tok, axis=0) \
            + self._positions(params, t - pos_offset)
        blk = params["blocks"]
        kpos = jnp.arange(T)
        allow = (kpos[None, :] <= t) \
            & (kpos[None, :] >= pos_offset[:, None])         # (B, T)
        for layer in range(cfg.n_layers):
            x = _rms(h, blk["ln1"][layer])
            q = (x @ blk["wq"][layer]).reshape(B, cfg.n_heads, cfg.d_head)
            k = x @ blk["wk"][layer]                         # (B, dh)
            v = x @ blk["wv"][layer]
            ck = lax.dynamic_update_slice(
                ck, k[None, :, None, :], (layer, 0, t, 0))
            cv = lax.dynamic_update_slice(
                cv, v[None, :, None, :], (layer, 0, t, 0))
            s = jnp.einsum("bhd,btd->bht", q, ck[layer]) \
                * (cfg.d_head ** -0.5)
            s = jnp.where(allow[:, None, :], s, _NEG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bht,btd->bhd", p, cv[layer])
            h = h + o.reshape(B, -1) @ blk["wo"][layer]
            x2 = _rms(h, blk["ln2"][layer])
            h = h + jax.nn.relu(x2 @ blk["w1"][layer]) @ blk["w2"][layer]
        logits = _rms(h, params["ln_f"]) @ params["embed"].T
        return logits.astype(jnp.float32), (ck, cv)

    def step_ragged(self, params, caches, tok, t):
        """One token for every row at PER-ROW positions: ``tok`` (B,)
        int32, ``t`` (B,) int32 — row ``b``'s token sits at cache
        position ``t[b]``.  Rows are origin-0 (ragged-round engine
        contract: token ``i`` lives at lane position ``i``), so the
        attention window is simply ``kpos <= t[b]`` and the learned
        position IS ``t[b]``.  Returns ``(logits (B, V) fp32, caches)``.

        The K/V write is a per-row scatter (rows advance raggedly, so
        no single ``dynamic_update_slice`` start exists); out-of-range
        positions drop, and a re-step of an already-written position
        overwrites it with identical values — the property the engine's
        frozen/done rows rely on."""
        cfg = self.cfg
        ck, cv = caches
        B = tok.shape[0]
        T = ck.shape[POS_AXIS]
        rows = jnp.arange(B)
        h = jnp.take(params["embed"], tok, axis=0) \
            + self._positions(params, t)
        blk = params["blocks"]
        kpos = jnp.arange(T)
        allow = kpos[None, :] <= t[:, None]                  # (B, T)
        for layer in range(cfg.n_layers):
            x = _rms(h, blk["ln1"][layer])
            q = (x @ blk["wq"][layer]).reshape(B, cfg.n_heads, cfg.d_head)
            k = x @ blk["wk"][layer]                         # (B, dh)
            v = x @ blk["wv"][layer]
            ck = ck.at[layer, rows, t].set(k, mode="drop")
            cv = cv.at[layer, rows, t].set(v, mode="drop")
            s = jnp.einsum("bhd,btd->bht", q, ck[layer]) \
                * (cfg.d_head ** -0.5)
            s = jnp.where(allow[:, None, :], s, _NEG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bht,btd->bhd", p, cv[layer])
            h = h + o.reshape(B, -1) @ blk["wo"][layer]
            x2 = _rms(h, blk["ln2"][layer])
            h = h + jax.nn.relu(x2 @ blk["w1"][layer]) @ blk["w2"][layer]
        logits = _rms(h, params["ln_f"]) @ params["embed"].T
        return logits.astype(jnp.float32), (ck, cv)

    def verify_ragged(self, params, caches, tok_chunk, t,
                      with_logits=True):
        """Chunk step at PER-ROW start positions: ``tok_chunk`` (B, C)
        with row ``b``'s chunk occupying positions ``[t[b], t[b]+C)``
        (origin-0 rows — the ragged-round contract).  Same semantics
        as :meth:`verify` otherwise: each chunk token writes its K/V
        and attends the full cache through its own position, so one
        weights read verifies C draft positions per row even when the
        rows' clocks disagree.  Returns ``(logits (B, C, V) | None,
        caches)``."""
        cfg = self.cfg
        ck, cv = caches
        B, C = tok_chunk.shape
        T = ck.shape[POS_AXIS]
        rows = jnp.arange(B)
        j = jnp.arange(C)
        pos = t[:, None] + j[None, :]                        # (B, C)
        h = jnp.take(params["embed"], tok_chunk, axis=0) \
            + self._positions(params, pos)
        blk = params["blocks"]
        kpos = jnp.arange(T)
        allow = kpos[None, None, :] <= pos[:, :, None]       # (B, C, T)
        for layer in range(cfg.n_layers):
            x = _rms(h, blk["ln1"][layer])
            q = (x @ blk["wq"][layer]).reshape(
                B, C, cfg.n_heads, cfg.d_head)
            k = x @ blk["wk"][layer]                     # (B, C, dh)
            v = x @ blk["wv"][layer]
            ck = ck.at[layer, rows[:, None], pos].set(k, mode="drop")
            cv = cv.at[layer, rows[:, None], pos].set(v, mode="drop")
            s = jnp.einsum("bchd,btd->bhct", q, ck[layer]) \
                * (cfg.d_head ** -0.5)
            s = jnp.where(allow[:, None], s, _NEG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhct,btd->bchd", p, cv[layer])
            h = h + o.reshape(B, C, -1) @ blk["wo"][layer]
            x2 = _rms(h, blk["ln2"][layer])
            h = h + jax.nn.relu(x2 @ blk["w1"][layer]) @ blk["w2"][layer]
        if not with_logits:
            return None, (ck, cv)
        logits = _rms(h, params["ln_f"]) @ params["embed"].T
        return logits.astype(jnp.float32), (ck, cv)

    def verify(self, params, caches, tok_chunk, t, pos_offset,
               with_logits=True):
        """Chunk step — the speculative VERIFY pass (and, without
        logits, the prefix-sharing suffix prefill): process
        ``tok_chunk`` (B, C) at global positions ``[t, t+C)``, writing
        each token's K/V and attending the FULL cache with the same
        ``[offset, position]`` validity window as :meth:`step`, so
        position ``t+i``'s logits condition on the cache through
        ``t-1`` plus chunk tokens ``<= i`` — one weights read verifies
        C draft positions.  Returns ``(logits (B, C, V) | None,
        caches)``.

        The key axis is the full cache buffer in both this and
        :meth:`step` (masked positions underflow to exact zero), which
        is what keeps chunk-verified logits token-compatible with the
        step-by-step decode they stand in for."""
        cfg = self.cfg
        ck, cv = caches
        B, C = tok_chunk.shape
        T = ck.shape[POS_AXIS]
        j = jnp.arange(C)
        h = jnp.take(params["embed"], tok_chunk, axis=0) \
            + self._positions(params,
                              t + j[None, :] - pos_offset[:, None])
        blk = params["blocks"]
        kpos = jnp.arange(T)
        allow = (kpos[None, None, :] <= (t + j)[None, :, None]) \
            & (kpos[None, None, :] >= pos_offset[:, None, None])
        for layer in range(cfg.n_layers):
            x = _rms(h, blk["ln1"][layer])
            q = (x @ blk["wq"][layer]).reshape(
                B, C, cfg.n_heads, cfg.d_head)
            k = x @ blk["wk"][layer]                     # (B, C, dh)
            v = x @ blk["wv"][layer]
            ck = lax.dynamic_update_slice(
                ck, k[None], (layer, 0, t, 0))
            cv = lax.dynamic_update_slice(
                cv, v[None], (layer, 0, t, 0))
            s = jnp.einsum("bchd,btd->bhct", q, ck[layer]) \
                * (cfg.d_head ** -0.5)
            s = jnp.where(allow[:, None], s, _NEG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhct,btd->bchd", p, cv[layer])
            h = h + o.reshape(B, C, -1) @ blk["wo"][layer]
            x2 = _rms(h, blk["ln2"][layer])
            h = h + jax.nn.relu(x2 @ blk["w1"][layer]) @ blk["w2"][layer]
        if not with_logits:
            return None, (ck, cv)
        logits = _rms(h, params["ln_f"]) @ params["embed"].T
        return logits.astype(jnp.float32), (ck, cv)

    def prefill(self, params, caches, toks, pos_offset):
        """Fill cache positions ``[0, Tq)`` from a ``(B, Tq)`` chunk in
        one causal pass (no logits — the cache fill is the product).
        Rows are RIGHT-aligned: chunk position ``j`` holds row token
        ``j - pos_offset[b]`` (pad positions write garbage K/V that the
        validity mask keeps unread — the ``models.decoding`` padded
        contract)."""
        cfg = self.cfg
        ck, cv = caches
        B, Tq = toks.shape
        j = jnp.arange(Tq)
        h = jnp.take(params["embed"], toks, axis=0) \
            + self._positions(params, j[None, :] - pos_offset[:, None])
        blk = params["blocks"]
        allow = (j[None, None, :] <= j[None, :, None]) \
            & (j[None, None, :] >= pos_offset[:, None, None])  # (B,Tq,Tq)
        for layer in range(cfg.n_layers):
            x = _rms(h, blk["ln1"][layer])
            q = (x @ blk["wq"][layer]).reshape(
                B, Tq, cfg.n_heads, cfg.d_head)
            k = x @ blk["wk"][layer]                         # (B, Tq, dh)
            v = x @ blk["wv"][layer]
            ck = lax.dynamic_update_slice(
                ck, k[None, :, :, :], (layer, 0, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, v[None, :, :, :], (layer, 0, 0, 0))
            s = jnp.einsum("bihd,bjd->bhij", q, k) * (cfg.d_head ** -0.5)
            s = jnp.where(allow[:, None], s, _NEG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhij,bjd->bihd", p, v)
            h = h + o.reshape(B, Tq, -1) @ blk["wo"][layer]
            x2 = _rms(h, blk["ln2"][layer])
            h = h + jax.nn.relu(x2 @ blk["w1"][layer]) @ blk["w2"][layer]
        return (ck, cv)
