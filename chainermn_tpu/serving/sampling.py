"""Keyed sampling for the serving decode tier.

The engine's decode round was argmax-only: exactness (every request's
tokens identical to its solo decode, whatever shares its rounds) is
the property the whole scheduler is pinned against, and sampling looks
like it breaks the oracle.  It doesn't — it moves it:

- **Greedy stays the exactness oracle.**  Requests without
  ``SamplingParams`` take the argmax path, byte-identical to before
  (the engine even keeps the original compiled round program for
  all-greedy rounds), and stay pinned token-identical to the
  engine-independent solo oracle — including when they share rounds
  with sampled requests.
- **Sampled requests are pinned by keyed replay.**  Every sampled
  request carries its own ``jax.random`` key stream; the key for its
  ``i``-th generated token is ``fold_in(request_key, i)`` — a pure
  function of the REQUEST (seed and token index), never of the slot,
  round timing, or what else is in the batch (under ragged rounds
  the token index IS the row's own position clock).
  Two runs of the same request under any scheduling produce the same
  tokens, and the test oracle replays them solo from ``(key,
  params)`` alone.

Filters follow the HF composition order the static decode paths
already use: temperature scaling, then top-k, then top-p, each
truncating the distribution the next one sees.  All functions are
pure ``jnp``, equally callable inside the engine's ``shard_map``
round program (vectorized over rows) and on plain arrays (the tests'
replay oracle) — same code path, which is what makes the replay pin
meaningful.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "filter_logits", "fold_keys",
           "sample_tokens"]

_NEG = -1e30     # finite mask value (the ring_attention/minilm convention)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy: ``submit(..., sampling=...)``.

    ``temperature`` must be > 0 — greedy is the ABSENCE of sampling
    (``sampling=None``), not a zero temperature, so the exactness
    oracle's population is unambiguous.  ``top_k=0`` / ``top_p=1.0``
    disable the respective filter; both compose (temperature, then
    top-k, then top-p — the HF order).  ``seed`` derives the
    request's private key stream; the same ``(seed, params, prompt)``
    replays bit-identically under ANY scheduling."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature <= 0.0:
            raise ValueError(
                f"temperature={self.temperature} must be > 0: greedy "
                "decoding is sampling=None, not temperature 0")
        if self.top_k < 0:
            raise ValueError(f"top_k={self.top_k} must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p={self.top_p} not in (0, 1]")

    def key(self):
        """The request's root key (host-side convenience)."""
        return jax.random.PRNGKey(self.seed)


def filter_logits(logits, top_k, top_p):
    """Truncate ``logits`` (..., V) to the top-k then top-p
    candidates, per row; filtered entries drop to the finite mask
    value.  ``top_k`` (int, <=0 disables) and ``top_p`` (float, >=1
    disables) broadcast over the leading axes, so per-request values
    ride as (S,) arrays through the engine's round program."""
    v = logits.shape[-1]
    top_k = jnp.asarray(top_k)
    top_p = jnp.asarray(top_p)
    if top_k.ndim:
        top_k = top_k[..., None]
    if top_p.ndim:
        top_p = top_p[..., None]
    desc = jnp.sort(logits, axis=-1)[..., ::-1]
    # -- top-k: keep entries >= the k-th largest (ties keep all) ------- #
    kth = jnp.take_along_axis(
        desc, jnp.broadcast_to(
            jnp.clip(top_k - 1, 0, v - 1),
            logits.shape[:-1] + (1,)).astype(jnp.int32), axis=-1)
    keep = (logits >= kth) | (top_k <= 0)
    out = jnp.where(keep, logits, _NEG)
    # -- top-p over the k-truncated distribution ----------------------- #
    # one permutation serves both the cumsum and the unsort, so tied
    # values keep/drop consistently
    order = jnp.argsort(-out, axis=-1, stable=True)
    probs = jax.nn.softmax(
        jnp.take_along_axis(out, order, axis=-1), axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    # keep while the mass BEFORE a token is < p (at least one survives)
    keep_sorted = (csum - probs) < top_p
    rank = jnp.argsort(order, axis=-1)
    keep_p = jnp.take_along_axis(keep_sorted, rank, axis=-1)
    return jnp.where(keep_p, out, _NEG)


def fold_keys(keys, data):
    """Per-row ``fold_in``: ``keys`` (S, 2) uint32 raw key data,
    ``data`` (S,) int32 — the sampled token's own index within its
    request, which is what makes the stream schedule-invariant."""
    return jax.vmap(jax.random.fold_in)(keys, data)


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """One token per row from ``logits`` (S, V): rows with
    ``temperature > 0`` sample from their filtered distribution with
    their own key; the rest take the argmax (the greedy oracle path —
    same values the greedy program computes).  All parameters are
    per-row arrays; returns (S,) int32."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) \
        / jnp.maximum(temperature, 1e-6)[:, None]
    filt = filter_logits(scaled, top_k, top_p)
    drawn = jax.vmap(jax.random.categorical)(keys, filt) \
        .astype(jnp.int32)
    return jnp.where(temperature > 0.0, drawn, greedy)
