"""Slot-based continuous batching engine over the block-paged KV cache.

Every decode mode in ``models.decoding`` serves ONE static batch per
``generate`` call: rows enter together, and the while-loop exits when
the LAST row finishes — a slot whose row hit EOS idles until the whole
batch drains, and a request that arrives mid-call waits for the next
batch.  Under ragged, continuously-arriving traffic (the ROADMAP's
millions-of-users scenario) both wastes are unbounded.  This engine
replaces the batch with SLOTS:

- a request **queue** with a scheduler policy hook (FCFS or
  shortest-prompt-first built in, or any callable);
- **admission**: a freed slot is refilled mid-stream — the new
  request's prompt is prefilled into pool blocks
  (:mod:`~chainermn_tpu.serving.kv_blocks`) and copy-on-admit
  gathered into the slot's contiguous cache lane;
- **per-row eviction**: a slot leaves the moment ITS row is done
  (EOS or token budget), not when the last row is;
- a **ragged decode round** program advancing every live slot up to
  ``round_tokens`` positions off its OWN position clock — the ONE
  compiled program property of the static cache is preserved (the
  cache stays the dense ``_make_cache`` layout and every program
  shape is fixed), but rows are origin-0 (token ``i`` lives at lane
  position ``i``) and carry per-row ``position`` / ``length`` /
  ``end`` vectors instead of sharing a global clock.  No shared
  horizon ever binds (``prompt_len - 1 + max_new <= horizon - 1`` by
  submit validation), so the old block-aligned rebase shift — and its
  prewarm and mid-serve stalls — is gone entirely;
- **chunked prefill inside the round**: admission stages a prompt one
  fixed-shape chunk per scheduler step through the adapter's
  chunk-attends-cache ``verify`` surface while other rows keep
  decoding, so a long co-scheduled prompt no longer moves a short
  prompt's TTFT; and **per-row speculation as a round mode**: with a
  ``draft_adapter`` attached, all-greedy rounds draft ``spec_k``
  tokens per row and verify them in one target pass, committing a
  DIFFERENT number of tokens per row (accepted prefix + one) — the
  ragged clocks are what let acceptance raggedness ride at all.

The engine is MODEL-AGNOSTIC: a decode adapter supplies
``make_cache`` / ``prefill`` / ``step`` (plus ``verify`` for the
chunk-attends-cache paths) and sharding specs (see
:class:`~chainermn_tpu.serving.minilm.MiniLMAdapter` for the protocol
example and :class:`TransformerAdapter` for the flagship).  Decoding
is greedy by default — which is what makes the engine's exactness
guarantee testable: every admitted request's tokens are
token-identical to its solo static decode, independent of what shares
its rounds (pinned in ``tests/serving_tests/test_engine.py``).  That
guarantee survives the production decode tier: PREFIX SHARING
(``prefix_sharing=True``) changes which physical blocks hold the KV,
never its attended content, and per-request KEYED SAMPLING
(``submit(sampling=...)``) moves only the opted-in rows off argmax —
greedy rows stay the pinned oracle while sampled rows pin by
(key, params) replay instead (:mod:`~chainermn_tpu.serving.sampling`).

Single-controller: results are fetched by host indexing into the
sharded token buffer, so every shard must be addressable from this
process (the 8-device CPU mesh and single-host TPU slices; multi-host
serving needs a fetch collective and is future work).

**Overload and failure.**  Requests carry optional ``deadline`` /
``timeout``, ``priority`` and ``tenant``; an attached
:class:`~chainermn_tpu.serving.admission.AdmissionController` bounds
the queue (with priority displacement), enforces per-tenant in-flight
token quotas, and fast-rejects requests whose predicted completion
would breach their deadline — each reject is a typed
:class:`~chainermn_tpu.serving.admission.ShedCompletion`, never an
unbounded queue.  Deadlines are enforced engine-side regardless:
expired queued requests shed ``"timeout"``, expired ACTIVE rows are
evicted mid-stream with their partial tokens and ``status="timeout"``;
:meth:`ServingEngine.cancel` drains a queued copy or frees the slot.
A failure in a per-request program (stage/admit) or in the shared
decode round quarantines the attributable (or newest-admitted)
request and keeps the remaining slots serving — see
docs/SERVING.md "Overload and admission" and docs/RESILIENCE.md.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
import time
import uuid
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel._compat import pcast, typeof
from chainermn_tpu.utils.metrics import get_registry
from chainermn_tpu.utils.programs import (
    get_accountant,
    get_ledger,
    ledger_jit,
    weakref_root,
)
from chainermn_tpu.utils.telemetry import RequestTraceStore, get_recorder

from . import kv_blocks as kvb
from .admission import AdmissionController, ShedCompletion
from .prefix_cache import RefcountedBlockPool
from .sampling import SamplingParams, fold_keys, sample_tokens

__all__ = ["Completion", "Request", "ServingEngine", "TransformerAdapter"]


def _vary(x, *axes):
    """Type ``x`` varying over ``axes`` on vma jax; identity pre-vma
    (``pcast``/``typeof`` resolve through the compat shims)."""
    need = tuple(a for a in axes if a not in typeof(x).vma)
    return pcast(x, need, to="varying") if need else x


@dataclasses.dataclass(eq=False)     # identity equality: ndarray fields
class Request:
    """One queued generation request (host-side).

    ``priority`` is a smaller-is-more-important class index (0 is the
    most important); ``deadline`` is an ABSOLUTE ``time.perf_counter``
    timestamp (``submit(timeout=...)`` converts); ``tenant`` names the
    quota bucket the request's ``max_new`` tokens count against.

    ``trace_id`` is the request's causal-trace identity: caller-
    propagated through ``submit(trace_id=...)`` (a front-end carrying
    a distributed-tracing id) or engine-generated when request tracing
    is on; it rides every ``serve/*`` histogram observation as the
    exemplar and names the retained timeline in the engine's
    :class:`~chainermn_tpu.utils.telemetry.RequestTraceStore`.
    ``spans`` is that timeline while the request is live — ``None``
    whenever tracing is off (the disabled path allocates nothing
    per request, pinned by test)."""

    rid: str
    prompt: np.ndarray          # (P,) int32
    max_new: int                # token budget (eos may end the row early)
    t_submit: float = 0.0
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    priority: int = 0
    tenant: Optional[str] = None
    deadline: Optional[float] = None
    trace_id: Optional[str] = None
    spans: Optional[list] = None
    #: per-request sampling policy (``None`` = greedy, the exactness
    #: oracle; see :mod:`~chainermn_tpu.serving.sampling`)
    sampling: Optional[SamplingParams] = None


@dataclasses.dataclass(eq=False)
class Completion:
    """A finished request: ``tokens`` are the GENERATED tokens only
    (first EOS kept when one was emitted, budget-truncated otherwise —
    the ``make_generate_fn`` convention).  The derived latency fields
    (``queue_wait`` / ``ttft`` / ``tpot`` / ``e2e``) are THE request
    record — ``ServingEngine.request_records()`` hands these back so
    callers (``SLOReport``, ``bench_serving``) stop recomputing them
    from raw timestamps.

    ``status`` is ``"ok"`` for a request served to EOS/budget;
    ``"timeout"`` / ``"cancelled"`` / ``"quarantined"`` rows were
    evicted MID-stream and carry whatever tokens they had generated
    (possibly none).  Such rows may never have produced a first token,
    so ``t_admit``/``t_first`` — and the latencies derived from them —
    can be ``None``; ``SLOReport`` skip-counts those instead of
    poisoning percentiles."""

    rid: str
    prompt: np.ndarray
    tokens: np.ndarray
    t_submit: float
    t_admit: Optional[float]
    t_first: Optional[float]
    t_done: float
    slot: int
    status: str = "ok"
    detail: str = ""
    trace_id: Optional[str] = None

    @property
    def n_generated(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def queue_wait(self) -> Optional[float]:
        """Submit → admission into a decode slot (where static
        batching bleeds)."""
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft(self) -> Optional[float]:
        """Time-to-first-token: submit → first generated token on host."""
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot(self) -> Optional[float]:
        """Time-per-output-token after the first (the decode steady
        state): ``(t_done - t_first) / (n_generated - 1)``."""
        if self.t_first is None:
            return None
        return (self.t_done - self.t_first) / max(self.n_generated - 1, 1)

    @property
    def e2e(self) -> float:
        """Submit → eviction with every token on host."""
        return self.t_done - self.t_submit


class TransformerAdapter:
    """Decode adapter binding the flagship transformer
    (``models.decoding``) to the serving engine.

    Shards like ``make_generate_fn``: batch over ``data×expert``,
    heads over ``model``, layers+cache over ``pipe``; params via
    ``param_specs``.  Requires vma-typed jax (``TransformerConfig``
    refuses to construct without it); on older jaxes use
    :class:`~chainermn_tpu.serving.minilm.MiniLMAdapter`.  MoE configs
    are rejected — router capacity depends on batch composition, which
    would break the engine's token-identity guarantee — and ``seq``
    meshes are rejected like every ``pos_offset`` path.
    """

    batch_axes = ("data", "expert")

    def __init__(self, mesh_cfg, cfg, *, quantized: bool = False):
        from chainermn_tpu.models.decoding import _decode_preamble

        if cfg.moe:
            raise ValueError(
                "MoE decode under continuous batching is not supported: "
                "router capacity couples rows, so a request's tokens "
                "would depend on what shares its rounds — the exactness "
                "guarantee the engine is built on")
        if mesh_cfg.mesh.shape.get("seq", 1) != 1:
            raise ValueError(
                "continuous batching drives per-row position origins "
                "(pos_offset), which seq-KV decode does not support: "
                "use a seq=1 mesh (shard batch/heads/layers instead)")
        # validates fsdp-off, pipe divisibility; local sizes for caches
        _, _, self._kv_heads_local, self._layers_local = \
            _decode_preamble(mesh_cfg, cfg, 0)
        self.mesh_cfg = mesh_cfg
        self.cfg = cfg
        self.quantized = quantized

    def param_specs(self):
        from chainermn_tpu.models import param_specs

        return param_specs(self.cfg, quantized=self.quantized)

    def cache_specs(self):
        spec = P("pipe", self.batch_axes, None, "model")
        n = 4 if self.cfg.kv_cache_dtype == "int8" else 2
        return (spec,) * n

    def make_cache(self, rows, kv_len, batch_varying=True):
        from chainermn_tpu.models.decoding import _make_cache

        return _make_cache(self.cfg, rows, kv_len, self._kv_heads_local,
                           self._layers_local,
                           batch_varying=batch_varying)

    def step(self, params, caches, tok, t, pos_offset):
        from chainermn_tpu.models.decoding import _decode_step

        return _decode_step(self.cfg, params, caches, tok, t,
                            pos_offset=pos_offset)

    def prefill(self, params, caches, toks, pos_offset):
        from chainermn_tpu.models.decoding import _decode_step

        _, caches = _decode_step(self.cfg, params, caches, toks, 0,
                                 with_logits=False,
                                 chunk_attends_cache=True,
                                 pos_offset=pos_offset)
        return caches

    def verify(self, params, caches, tok_chunk, t, pos_offset,
               with_logits=True):
        """Chunk step at positions ``[t, t+C)`` attending the cache —
        the speculative verify pass (logits for every chunk position)
        and, without logits, the prefix-sharing suffix prefill.  Rides
        ``_decode_step``'s chunk path, so it carries the same vma
        requirement as every ``TransformerConfig`` program."""
        from chainermn_tpu.models.decoding import _decode_step

        logits, caches = _decode_step(
            self.cfg, params, caches, tok_chunk, t,
            all_logits=with_logits, with_logits=with_logits,
            chunk_attends_cache=True, pos_offset=pos_offset)
        return (logits if with_logits else None), caches

    def step_ragged(self, params, caches, tok, t):
        """Per-row-position decode step (the ragged-round engine
        contract; see ``MiniLMAdapter.step_ragged``).  The flagship
        ``_decode_step`` advances every row at one scalar position, so
        the ragged form needs per-row position support in
        ``models.decoding``'s vma path — not landed yet."""
        raise NotImplementedError(
            "TransformerAdapter does not implement the ragged decode "
            "step: models.decoding._decode_step takes one scalar "
            "position for the whole batch.  Ragged serving needs the "
            "per-row-position decode path (future models.decoding "
            "work); MiniLMAdapter is the runnable ragged reference.")

    def verify_ragged(self, params, caches, tok_chunk, t,
                      with_logits=True):
        """Per-row-start chunk verify (ragged speculation); same gap
        as :meth:`step_ragged`."""
        raise NotImplementedError(
            "TransformerAdapter does not implement the ragged chunk "
            "verify: models.decoding's chunk path takes one scalar "
            "start position.  MiniLMAdapter is the runnable ragged "
            "reference.")


def _fcfs(queue: Sequence[Request], engine) -> Request:
    return queue[0]


def _spf(queue: Sequence[Request], engine) -> Request:
    """Shortest-prompt-first.  Ties break by SUBMIT ORDER explicitly
    (the queue is submission-ordered), so a seeded trace admits
    identically on every run — pinned by test."""
    return min(enumerate(queue),
               key=lambda t: (t[1].prompt.shape[0], t[0]))[1]


def _deadline(queue: Sequence[Request], engine) -> Request:
    """Deadline-aware: admit the request whose deadline is TIGHTEST
    relative to its predicted remaining service time (least slack
    first), within priority classes (class 0 always outranks class 1).

    Slack is ``(deadline - now) - predictor.predict_remaining(max_new)``
    via the attached admission controller's service-time predictor;
    without a controller (or while the predictor is cold) it degrades
    to earliest-deadline-first.  Deadline-less requests sort after all
    deadlined ones of their class, in submit order.  Every tie breaks
    by submit order — deterministic across runs of one seeded trace
    (pinned by test)."""
    now = time.perf_counter()
    ctrl = getattr(engine, "admission", None)
    pred = ctrl.predictor if ctrl is not None else None

    def key(t):
        i, r = t
        if r.deadline is None:
            return (r.priority, 1, 0.0, i)
        rem = pred.predict_remaining(r.max_new) if pred is not None \
            else None
        slack = (r.deadline - now) - (rem if rem is not None else 0.0)
        return (r.priority, 0, slack, i)

    return min(enumerate(queue), key=key)[1]


def _wfq(queue: Sequence[Request], engine) -> Request:
    """Weighted fair queuing across tenants: the attached admission
    controller's deficit-round-robin pick (tenant weights, quantum
    state) within the most important priority class present.  Requires
    a controller — WFQ without per-tenant state is FCFS wearing a
    costume."""
    ctrl = getattr(engine, "admission", None)
    if ctrl is None:
        raise ValueError(
            "policy 'wfq' needs an AdmissionController attached "
            "(engine.admission) to hold the per-tenant DRR state")
    return ctrl.wfq_pick(queue)


_POLICIES = {"fcfs": _fcfs, "spf": _spf, "deadline": _deadline,
             "wfq": _wfq}


def _trace_store_from_env() -> Optional[RequestTraceStore]:
    """The env-gated default request-trace store (the TraceRecorder /
    MetricsRegistry discipline: off unless ``CHAINERMN_TPU_REQUEST_
    TRACE=1``; a typo'd knob degrades to the default, never crashes)."""
    if os.environ.get("CHAINERMN_TPU_REQUEST_TRACE", "") in ("", "0"):
        return None

    def _num(name, default, conv):
        try:
            return conv(os.environ[name])
        except (KeyError, ValueError, TypeError):
            return default

    cap = max(_num("CHAINERMN_TPU_REQUEST_TRACE_CAPACITY", 256, int), 1)
    rate = min(max(
        _num("CHAINERMN_TPU_REQUEST_TRACE_SAMPLE", 0.05, float), 0.0),
        1.0)
    slo = _num("CHAINERMN_TPU_REQUEST_TRACE_SLO", None, float)
    return RequestTraceStore(capacity=cap, sample_rate=rate,
                             slo_e2e=slo)


class ServingEngine:
    """Continuous-batching scheduler around one decode adapter.

    Args:
      adapter: decode backend (``MiniLMAdapter`` / ``TransformerAdapter``).
      params: model parameters (host or device); placed replicated /
        per ``adapter.param_specs()`` once at construction.
      n_slots: concurrent decode rows; must divide evenly over the
        mesh's batch shards.
      horizon: the dense cache's position capacity.  Rows are
        origin-0 and carry their own position clocks in
        ``[0, horizon)``; submit validation guarantees
        ``prompt_len - 1 + max_new <= horizon - 1``, so no rebase
        machinery exists — a freed slot simply restarts at 0.
      max_prompt: longest admissible prompt; rounded up to a block
        multiple internally (``Pq``) — every prompt stages into
        ``ceil(P/block)`` pool blocks and admission gathers ONE
        fixed-shape ``Pq`` chunk into lane positions ``[0, Pq)``, so
        admission is ONE compiled program, not one per length.
      block: position-block size of the staging pool.
      pool_blocks: staging-pool capacity in blocks (default: one full
        ``Pq`` chunk per slot).  A staged request holds only
        ``ceil(P/block)`` blocks — its real footprint — so a deep
        ragged queue stages many more requests than slots.
      eos_id / pad_id: early-stop token semantics, exactly
        ``make_generate_fn``'s (first EOS kept, frozen rows emit pad).
      round_tokens: decode-round length — positions advanced per
        dispatch; the host observes the per-row done bitmap between
        rounds (larger = less dispatch overhead, more post-EOS waste).
      prefill_chunk: chunked-admission budget in BLOCKS — while other
        rows are decoding, a staging prompt advances at most this many
        prompt blocks per scheduler step through the adapter's
        ``verify`` chunk-attends-cache surface (one fixed-shape
        program for every chunk of every split, so chunked admission
        never retraces).  With NO live rows the whole prompt stages in
        one step regardless (nothing to interleave with).  Default 1
        block; adapters without ``verify`` fall back to the monolithic
        prefill program.
      draft_adapter / draft_params: attach a DRAFT model and turn
        per-row speculative draft/verify into a round MODE: all-greedy
        rounds draft ``spec_k`` tokens per row with the draft model,
        verify them in one target ``verify_ragged`` pass, and commit a
        per-row accepted-prefix-plus-one token count — token-identical
        to greedy decode whatever the draft proposes.  Rounds with a
        SAMPLED row live fall back to per-token rounds (keyed-replay
        sampling and speculative commits do not compose).  The draft
        adapter must share the target's mesh/batch axes.
      spec_k: draft tokens per speculative round (>= 1).
      policy: ``"fcfs"``, ``"spf"``, or ``callable(queue, engine) ->
        Request`` choosing the next admission from the queue.
      gang: static-batching mode — admit only when EVERY slot is free
        (the whole gang drains before the next forms).  This is the
        bench's baseline arm: same programs, same dispatch granularity,
        only the scheduling differs.
      prefill_ahead: stage up to this many queued requests' prompts
        into the pool while slots are still busy (0 disables; default
        ``n_slots``).  Admission of a staged request skips the prefill
        compute — only the copy-on-admit gather remains.
      record_history: how many completed requests
        :meth:`request_records` retains (a bounded ring — a
        long-running server must not grow a completion list without
        bound; completions returned from :meth:`step` are unaffected).
        0 disables retention.
      policy: ``"fcfs"``, ``"spf"``, ``"deadline"`` (least slack vs
        predicted service time, within priority classes), or
        ``callable(queue, engine) -> Request``.
      admission: optional
        :class:`~chainermn_tpu.serving.admission.AdmissionController`
        — queue bound + priority displacement, per-tenant in-flight
        token quotas, predictive deadline shedding.  Host-side only
        and swappable between runs (``engine.admission = ...``, like
        ``gang``); ``None`` admits everything, bounded only by
        deadlines the requests themselves carry.
      epoch: the serving epoch this engine admits for (the elastic
        membership epoch — docs/SERVING.md "Epoch drains").  A submit
        carrying an OLDER epoch is shed ``"stale_epoch"``; during a
        :meth:`drain` every submit is shed ``"draining"`` with a
        ``retry_after`` from the predictor's queue-drain estimate;
        :meth:`complete_drain` re-opens admission under the new epoch.
      traces: a
        :class:`~chainermn_tpu.utils.telemetry.RequestTraceStore` —
        turns ON per-request causal tracing: every request gets a
        ``trace_id`` (caller-propagated or generated), its lifecycle
        spans (``queue_wait``/``admit``/``prefill`` or
        ``chunk_prefill``/sampled ``decode_round``/terminal) are
        assembled into a
        timeline offered to the store at eviction/shed (tail-based
        retention there), and every ``serve/*`` histogram observation
        carries the trace id as its EXEMPLAR — a p99 on the dashboard
        resolves to the offending request's trace.  Default ``None``
        (off; the per-request cost is zero allocations, pinned by
        test) unless ``CHAINERMN_TPU_REQUEST_TRACE=1`` is set, which
        builds a store from ``CHAINERMN_TPU_REQUEST_TRACE_CAPACITY``
        / ``_SAMPLE`` / ``_SLO``.
      trace_decode_every: per-request decode-round span sampling — a
        traced request's FIRST round is always in its timeline (the
        TTFT cause), later rounds every N-th (a 1000-token decode must
        not be a 1000-span trace).
      prefix_sharing: copy-on-write prefix sharing over the staging
        pool (docs/SERVING.md "Prefix sharing"; default ON).  Staged
        blocks are refcounted and content-addressed by token prefix:
        requests sharing a prompt prefix hold ONE physical copy of
        its full blocks and prefill only their divergent suffix, and
        a completed request's full blocks stay cached for the next
        arrival (LRU-reclaimed under pool pressure).  Greedy decode
        stays token-bitwise identical to the private-KV path (pinned);
        ``False`` restores strictly private per-request blocks.
    """

    def __init__(self, adapter, params, *, n_slots: int, horizon: int,
                 max_prompt: int, block: int = 16,
                 pool_blocks: Optional[int] = None, eos_id: int = -1,
                 pad_id: int = 0, round_tokens: int = 4,
                 policy: Union[str, Callable] = "fcfs",
                 gang: bool = False,
                 prefill_ahead: Optional[int] = None,
                 default_max_new: int = 32,
                 record_history: int = 4096,
                 admission: Optional[AdmissionController] = None,
                 epoch: int = 0,
                 traces: Optional[RequestTraceStore] = None,
                 trace_decode_every: int = 4,
                 prefix_sharing: bool = True,
                 prefill_chunk: int = 1,
                 draft_adapter=None, draft_params=None,
                 spec_k: int = 4):
        mesh = adapter.mesh_cfg.mesh
        if not callable(getattr(adapter, "step_ragged", None)):
            raise ValueError(
                f"{type(adapter).__name__} has no step_ragged: the "
                "ragged decode round advances every row at its own "
                "position, which the adapter must implement (see "
                "MiniLMAdapter.step_ragged for the contract)")
        if (draft_adapter is None) != (draft_params is None):
            raise ValueError(
                "draft_adapter and draft_params come together — give "
                "both (speculative round mode) or neither")
        if draft_adapter is not None:
            if spec_k < 1:
                raise ValueError(f"spec_k={spec_k} must be >= 1")
            if draft_adapter.mesh_cfg.mesh is not mesh \
                    or tuple(draft_adapter.batch_axes) \
                    != tuple(adapter.batch_axes):
                raise ValueError(
                    "draft_adapter must share the target adapter's "
                    "mesh and batch axes (its cache rides the same "
                    "slot sharding)")
            if not callable(getattr(adapter, "verify_ragged", None)):
                raise ValueError(
                    f"{type(adapter).__name__} has no verify_ragged: "
                    "per-row speculation verifies each row's draft "
                    "chunk at its own start position")
        shards = 1
        for a in adapter.batch_axes:
            shards *= mesh.shape.get(a, 1)
        if n_slots < 1 or n_slots % shards:
            raise ValueError(
                f"n_slots={n_slots} must be a positive multiple of the "
                f"batch shard count {shards} (mesh axes "
                f"{adapter.batch_axes})")
        if block < 1 or max_prompt < 1:
            raise ValueError(
                f"block={block} and max_prompt={max_prompt} must be >= 1")
        self._pq = kvb.blocks_needed(max_prompt, block) * block
        if horizon < self._pq + 1:
            raise ValueError(
                f"horizon={horizon} must exceed the padded prompt "
                f"chunk {self._pq}")
        self._w = self._pq // block
        if pool_blocks is None:
            pool_blocks = n_slots * self._w
        if pool_blocks < self._w:
            raise ValueError(
                f"pool_blocks={pool_blocks} cannot stage even one "
                f"{self._w}-block prompt chunk")
        if eos_id >= 0 and pad_id < 0:
            raise ValueError(f"pad_id={pad_id} must be >= 0 with eos")
        if round_tokens < 1:
            raise ValueError(f"round_tokens={round_tokens} must be >= 1")
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be >= 1 (blocks)")
        self.set_policy(policy)
        self.adapter = adapter
        self.n_slots = n_slots
        self.horizon = horizon
        self.max_prompt = max_prompt
        self.block = block
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.round_tokens = round_tokens
        self.gang = gang
        self.prefill_ahead = n_slots if prefill_ahead is None \
            else prefill_ahead
        self.default_max_new = default_max_new
        self.admission = admission
        self.epoch = int(epoch)
        if traces is None:
            traces = _trace_store_from_env()
        self.traces = traces
        if trace_decode_every < 1:
            raise ValueError(
                f"trace_decode_every={trace_decode_every} must be >= 1")
        self.trace_decode_every = int(trace_decode_every)
        if record_history < 0:
            raise ValueError(
                f"record_history={record_history} must be >= 0")
        self.record_history = record_history
        self._n_local = n_slots // shards
        self._n_shards = shards
        self._mesh = mesh
        self._params = jax.device_put(
            params, jax.tree.map(
                lambda s: NamedSharding(mesh, s), adapter.param_specs(),
                is_leaf=lambda x: isinstance(x, P)))
        self.prefix_sharing = bool(prefix_sharing)
        # chunked (and suffix-resumed) prefill needs the adapter's
        # chunk-attends-cache verify surface; without it staging falls
        # back to one monolithic prefill per prompt (prefix hits still
        # share blocks, they just re-prefill the whole chunk)
        self._can_suffix = hasattr(adapter, "verify")
        self.prefill_chunk = min(int(prefill_chunk), self._w)
        self._chunk_tokens = self.prefill_chunk * block
        self.draft_adapter = draft_adapter
        self.spec_k = int(spec_k)
        if draft_adapter is not None:
            self._draft_params = jax.device_put(
                draft_params, jax.tree.map(
                    lambda s: NamedSharding(mesh, s),
                    draft_adapter.param_specs(),
                    is_leaf=lambda x: isinstance(x, P)))
        self._alloc = RefcountedBlockPool(pool_blocks, block,
                                          share=self.prefix_sharing)
        self._build_programs()
        # reusable host staging for the admit path.  These buffers are
        # REWRITTEN per admission; everything handed to a jitted call
        # is copied first (_staging_copy) — a deferred sharded
        # device_put may alias host memory and block_until_ready does
        # not force the copy (the iterators.prefetch.put_window
        # hazard), so the transfer could still be reading the buffer
        # when the next admission rewrites it.
        self._lprompt_staging = np.zeros((self._pq,), np.int32)
        self._ids_staging = np.zeros((self._w,), np.int32)
        self.reset()

    # ------------------------------------------------------------------ #
    # compiled programs
    # ------------------------------------------------------------------ #

    def _shard_base(self):
        idx = 0
        for a in self.adapter.batch_axes:
            idx = idx * lax.axis_size(a) + lax.axis_index(a)
        return idx * self._n_local

    def _build_programs(self):
        ad = self.adapter
        mesh = self._mesh
        bax = ad.batch_axes
        cspecs = tuple(ad.cache_specs())

        def pool_spec(s):
            t = tuple(s)
            if len(t) <= kvb.ROW_AXIS:
                return P(*t)
            return P(*(t[:kvb.ROW_AXIS] + (None,)
                       + t[kvb.ROW_AXIS + 1:]))

        pool_specs = tuple(pool_spec(s) for s in cspecs)
        row_spec = P(bax)            # (n_slots,) and (n_slots, horizon)
        pspecs = ad.param_specs()
        S, H, R = self._n_local, self.horizon, self.round_tokens
        eos, pad, pq = self.eos_id, self.pad_id, self._pq

        def init_body():
            caches = tuple(_vary(c, *bax)
                           for c in ad.make_cache(S, H))
            buf = _vary(jnp.zeros((S, H), jnp.int32), *bax)
            return caches, buf

        self._init_fn = ledger_jit(jax.shard_map(
            init_body, mesh=mesh, in_specs=(),
            out_specs=(cspecs, row_spec)), label="serve/init")

        def pool_body():
            comps = ad.make_cache(1, pq, batch_varying=False)
            return tuple(
                jnp.zeros((c.shape[0], self._alloc.n_blocks, self.block)
                          + c.shape[3:], c.dtype)
                for c in comps)

        self._pool_init_fn = ledger_jit(jax.shard_map(
            pool_body, mesh=mesh, in_specs=(), out_specs=pool_specs),
            label="serve/pool_init")

        rows = jnp.arange(S)

        def ragged_step(params, caches, buf, pos, done, end, sample):
            """One ragged position per LIVE row: read each row's token
            at its OWN position, step, write the next token at
            ``pos + 1``, advance.  Done (and empty) rows re-step their
            frozen position — the rewrite is value-identical (same
            token, same attended prefix), which is what makes the
            frozen rows free instead of needing a gather/compact."""
            pc = jnp.clip(pos, 0, H - 1)
            tok = jnp.take_along_axis(buf, pc[:, None], axis=1)[:, 0]
            logits, caches = ad.step_ragged(params, caches, tok, pc)
            nxt = sample(logits, pos)
            new_done = done
            if eos >= 0:
                new_done = new_done | (nxt == eos)
            new_done = new_done | ((pos + 1) >= end)
            # live rows never clip (pos + 1 <= end <= H - 1); done
            # rows route their write OUT of bounds instead of onto a
            # clamped live position
            wpos = jnp.where(done, H, jnp.clip(pos + 1, 0, H - 1))
            buf = buf.at[rows, wpos].set(nxt, mode="drop")
            pos = jnp.where(done, pos, pos + 1)
            return caches, buf, pos, new_done

        def round_body(params, caches, buf, pos, done, end):
            def greedy(logits, _pos):
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def one(carry, _):
                carry = ragged_step(params, *carry, end, greedy)
                return carry, None

            (caches, buf, pos, done), _ = lax.scan(
                one, (caches, buf, pos, done), None, length=R)
            return caches, buf, pos, done

        self._round_fn = ledger_jit(
            jax.shard_map(
                round_body, mesh=mesh,
                in_specs=(pspecs, cspecs, row_spec, row_spec, row_spec,
                          row_spec),
                out_specs=(cspecs, row_spec, row_spec, row_spec)),
            label="serve/round", donate_argnums=(1, 2))

        def round_sampled_body(params, caches, buf, pos, done, end,
                               temp, topk, topp, keys):
            # the greedy round plus per-request keyed sampling: rows
            # with temperature 0 take the argmax values the greedy
            # program computes; sampled rows draw with the key folded
            # by their OWN token index — under origin-0 lanes that IS
            # ``pos + 1`` (the new token's row-local index), the same
            # stream the lockstep engine folded as ``t + 1 - offset``,
            # so keyed replay stays bit-identical across the redesign
            def sample(logits, pos):
                step_keys = fold_keys(keys, pos + 1)
                return sample_tokens(logits, step_keys, temp, topk,
                                     topp)

            def one(carry, _):
                carry = ragged_step(params, *carry, end, sample)
                return carry, None

            (caches, buf, pos, done), _ = lax.scan(
                one, (caches, buf, pos, done), None, length=R)
            return caches, buf, pos, done

        self._round_sampled_fn = ledger_jit(
            jax.shard_map(
                round_sampled_body, mesh=mesh,
                in_specs=(pspecs, cspecs, row_spec, row_spec, row_spec,
                          row_spec, row_spec, row_spec, row_spec,
                          row_spec),
                out_specs=(cspecs, row_spec, row_spec, row_spec)),
            label="serve/round_sampled", donate_argnums=(1, 2))

        def admit_body(caches, buf, pools, flat, prompt, slot):
            # position-level gather: the staged prompt is LEFT-aligned
            # in the pool (shareable block identity) and lands
            # LEFT-aligned in its lane too — origin-0 rows, token i at
            # position i, so admission is a straight gather at dst 0
            ls = slot - self._shard_base()
            ok = (ls >= 0) & (ls < S)
            lsc = jnp.clip(ls, 0, S - 1)
            caches = tuple(
                kvb.insert_chunk(c, kvb.gather_positions(pc, flat),
                                 lsc, 0, ok)
                for c, pc in zip(caches, pools))
            cur = lax.dynamic_slice(buf, (lsc, 0), (1, pq))
            row = jnp.where(ok, prompt[None], cur)
            buf = lax.dynamic_update_slice(buf, row, (lsc, 0))
            return caches, buf

        self._admit_fn = ledger_jit(
            jax.shard_map(
                admit_body, mesh=mesh,
                in_specs=(cspecs, row_spec, pool_specs, P(), P(), P()),
                out_specs=(cspecs, row_spec)),
            label="serve/admit", donate_argnums=(0, 1))

        C = self._chunk_tokens
        M = pq + C                  # materialized staging-row width

        def chunk_prefill_body(params, pools, flat, toks, t, ids,
                               valid):
            # ONE fixed-shape program for EVERY prefill chunk: the
            # chunk start ``t`` is a traced scalar, so every chunk of
            # every (prefix, suffix) split — block-aligned or resumed
            # mid-block after a sub-block copy — reuses one compile
            # (the per-split suffix-prefill retrace family this
            # replaces is dead).  Gather the row's staged content
            # ([0, t) real: shared prefix + earlier chunks + any
            # copied partial block), chunk-step ``toks`` at positions
            # [t, t+C) through the verify surface, and scatter back
            # the block-aligned window covering the chunk.
            caches = tuple(kvb.gather_positions(pc, flat)
                           for pc in pools)
            _, caches = ad.verify(params, caches, toks[None], t,
                                  jnp.zeros((1,), jnp.int32),
                                  with_logits=False)
            t0 = (t // self.block) * self.block
            # t <= pq - 1 so t0 + C + block <= pq + C = M: the window
            # slice never clamps (which would misalign it with ids)
            window = tuple(
                lax.dynamic_slice_in_dim(c, t0, C + self.block,
                                         axis=kvb.POS_AXIS)
                for c in caches)
            return tuple(
                kvb.scatter_chunk(pc, kvb.chunk_to_blocks(w, self.block),
                                  ids, valid)
                for pc, w in zip(pools, window))

        if self._can_suffix:
            self._chunk_prefill_fn = ledger_jit(
                jax.shard_map(
                    chunk_prefill_body, mesh=mesh,
                    in_specs=(pspecs, pool_specs, P(), P(), P(), P(),
                              P()),
                    out_specs=pool_specs),
                label="serve/chunk_prefill", donate_argnums=(1,))
        else:
            # no chunk-attends-cache surface: monolithic left-aligned
            # prefill per prompt (the pre-chunking fallback)
            def prefill_body(params, pools, prompt, ids, valid):
                caches = ad.make_cache(1, pq, batch_varying=False)
                caches = ad.prefill(params, caches, prompt[None],
                                    jnp.zeros((1,), jnp.int32))
                return tuple(
                    kvb.scatter_chunk(
                        pc, kvb.chunk_to_blocks(c, self.block), ids,
                        valid)
                    for pc, c in zip(pools, caches))

            self._prefill_fn = ledger_jit(
                jax.shard_map(
                    prefill_body, mesh=mesh,
                    in_specs=(pspecs, pool_specs, P(), P(), P()),
                    out_specs=pool_specs),
                label="serve/prefill", donate_argnums=(1,))

        def fork_body(pools, src, dst):
            # copy-on-write: duplicate one physical block so a row can
            # write privately while other holders keep the original
            # (also the sub-block fork's device copy)
            return tuple(kvb.copy_block(pc, src, dst, jnp.asarray(True))
                         for pc in pools)

        self._fork_fn = ledger_jit(
            jax.shard_map(
                fork_body, mesh=mesh,
                in_specs=(pool_specs, P(), P()), out_specs=pool_specs),
            label="serve/fork", donate_argnums=(0,))

        if self.draft_adapter is not None:
            self._build_spec_programs(mesh, bax, row_spec, pspecs,
                                      cspecs)

    def _build_spec_programs(self, mesh, bax, row_spec, pspecs,
                             cspecs):
        """The speculative round MODE's programs: draft-lane init and
        prefill, plus the draft/verify round itself."""
        ad, d_ad = self.adapter, self.draft_adapter
        S, H, K = self._n_local, self.horizon, self.spec_k
        eos, pq = self.eos_id, self._pq
        d_pspecs = d_ad.param_specs()
        d_cspecs = tuple(d_ad.cache_specs())
        rows = jnp.arange(S)

        def draft_init_body():
            return tuple(_vary(c, *bax) for c in d_ad.make_cache(S, H))

        self._draft_init_fn = ledger_jit(jax.shard_map(
            draft_init_body, mesh=mesh, in_specs=(),
            out_specs=d_cspecs), label="serve/draft_init")

        def draft_prefill_body(d_params, d_caches, prompt, slot):
            # the draft model has no staging pool: its cache is
            # per-slot only, rebuilt by one monolithic prefill of the
            # LEFT-aligned prompt row at each admit
            ls = slot - self._shard_base()
            ok = (ls >= 0) & (ls < S)
            lsc = jnp.clip(ls, 0, S - 1)
            comps = d_ad.make_cache(1, pq, batch_varying=False)
            comps = d_ad.prefill(d_params, comps, prompt[None],
                                 jnp.zeros((1,), jnp.int32))
            return tuple(
                kvb.insert_chunk(c, nc.astype(c.dtype), lsc, 0, ok)
                for c, nc in zip(d_caches, comps))

        self._draft_prefill_fn = ledger_jit(
            jax.shard_map(
                draft_prefill_body, mesh=mesh,
                in_specs=(d_pspecs, d_cspecs, P(), P()),
                out_specs=d_cspecs),
            label="serve/draft_prefill", donate_argnums=(1,))

        def round_spec_body(params, d_params, caches, d_caches, buf,
                            pos, done, end):
            # one speculative round: K ragged draft steps, ONE target
            # verify pass over each row's (K+1)-token chunk at its own
            # start, per-row accepted-prefix commit.  Committed tokens
            # come ONLY from the target's logits, so greedy token
            # identity holds whatever the draft proposes; stale
            # draft/target K/V beyond a row's commit point is
            # rewritten by that position's next step before anything
            # attends it (the same written-before-attended argument
            # the ragged round rests on).
            def draft_one(carry, _):
                d_caches, buf, dpos = carry
                pc = jnp.clip(dpos, 0, H - 1)
                tok = jnp.take_along_axis(buf, pc[:, None],
                                          axis=1)[:, 0]
                logits, d_caches = d_ad.step_ragged(
                    d_params, d_caches, tok, pc)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                wpos = jnp.where(done, H,
                                 jnp.clip(dpos + 1, 0, H - 1))
                buf = buf.at[rows, wpos].set(nxt, mode="drop")
                dpos = jnp.where(done, dpos, dpos + 1)
                return (d_caches, buf, dpos), None

            (d_caches, buf, _), _ = lax.scan(
                draft_one, (d_caches, buf, pos), None, length=K)

            j1 = jnp.arange(K + 1)
            cpos = jnp.clip(pos[:, None] + j1[None, :], 0, H - 1)
            chunk = jnp.take_along_axis(buf, cpos, axis=1)
            logits, caches = ad.verify_ragged(
                params, caches, chunk, jnp.clip(pos, 0, H - 1))
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # accepted = longest drafted prefix the target agrees
            # with; commit that prefix plus the target's one bonus
            # token, clipped to the row's remaining budget
            match = jnp.cumprod(
                (chunk[:, 1:] == g[:, :K]).astype(jnp.int32), axis=1)
            a = jnp.sum(match, axis=1)
            c = jnp.minimum(a + 1, jnp.maximum(end - pos, 1))
            if eos >= 0:
                iseos = g == eos
                first = jnp.where(iseos.any(axis=1),
                                  jnp.argmax(iseos, axis=1), K + 1)
                c = jnp.minimum(c, first + 1)
            # commit: scatter the c target tokens at pos+1..pos+c;
            # uncommitted lanes route out of bounds (a clamped write
            # could collide with a committed one nondeterministically)
            wmask = (~done[:, None]) & (j1[None, :] < c[:, None])
            wpos = jnp.where(wmask, pos[:, None] + 1 + j1[None, :], H)
            buf = buf.at[rows[:, None], wpos].set(g, mode="drop")
            pos2 = jnp.where(done, pos, pos + c)
            new_done = done | (pos2 >= end)
            if eos >= 0:
                hit = jnp.take_along_axis(
                    g, jnp.clip(c - 1, 0, K)[:, None], axis=1)[:, 0] \
                    == eos
                new_done = new_done | ((~done) & hit)
            acc = jnp.where(done, 0, a).astype(jnp.int32)
            com = jnp.where(done, 0, c).astype(jnp.int32)
            return caches, d_caches, buf, pos2, new_done, acc, com

        self._round_spec_fn = ledger_jit(
            jax.shard_map(
                round_spec_body, mesh=mesh,
                in_specs=(pspecs, d_pspecs, cspecs, d_cspecs, row_spec,
                          row_spec, row_spec, row_spec),
                out_specs=(cspecs, d_cspecs, row_spec, row_spec,
                           row_spec, row_spec, row_spec)),
            label="serve/round_spec", donate_argnums=(2, 3, 4))

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def reset(self) -> None:
        """(Re)initialize device and scheduler state, keeping the
        compiled programs — benches reuse one engine across arms."""
        self._caches, self._buf = self._init_fn()
        self._pools = self._pool_init_fn()
        if not self._buf.is_fully_addressable:
            raise RuntimeError(
                "ServingEngine needs every shard addressable from this "
                "process (single-controller serving); multi-host result "
                "fetch is not implemented")
        self._alloc = RefcountedBlockPool(self._alloc.n_blocks,
                                          self.block,
                                          share=self.prefix_sharing)
        self._queue: collections.deque = collections.deque()
        self._staged = {}           # rid -> (flat (Pq,), prompt_row (Pq,))
        self._chunking = {}         # rid -> in-flight chunk-prefill job
        self._slot_req: List[Optional[Request]] = [None] * self.n_slots
        # per-row ragged clocks, origin-0 lanes: token i at position i.
        # _pos = the row's CURRENT position (its token there is the
        # next step's input), _plen = prompt length, _end = the last
        # position the row may reach (_plen - 1 + max_new <= H - 1 by
        # submit validation).  Empty slots: pos 0, done.
        self._pos = np.zeros((self.n_slots,), np.int32)
        self._plen = np.zeros((self.n_slots,), np.int32)
        self._end = np.zeros((self.n_slots,), np.int32)
        self._done = np.ones((self.n_slots,), bool)
        # per-slot sampling state (zeros = greedy row); the sampled
        # round program runs only while a sampled row is live
        self._s_temp = np.zeros((self.n_slots,), np.float32)
        self._s_topk = np.zeros((self.n_slots,), np.int32)
        self._s_topp = np.ones((self.n_slots,), np.float32)
        self._s_keys = np.zeros((self.n_slots, 2), np.uint32)
        self._n_sampled_active = 0
        self._slot_status: List[str] = ["ok"] * self.n_slots
        self._slot_detail: List[str] = [""] * self.n_slots
        if self.draft_adapter is not None:
            self._draft_caches = self._draft_init_fn()
        self._pending_first: set = set()
        self._pending_shed: List[ShedCompletion] = []
        self._tenant_tokens: collections.Counter = collections.Counter()
        self._charged: set = set()      # rids counted in _tenant_tokens
        self._next_rid = 0
        self.admit_log: List[str] = []
        self._records: collections.deque = collections.deque(
            maxlen=self.record_history)
        self.n_rounds = 0
        self._round_capacity = 0        # token-slots offered by rounds
        self.spec_drafted = 0           # draft tokens proposed (spec mode)
        self.spec_accepted = 0          # draft tokens the target accepted
        self.n_chunk_prefills = 0       # prompt chunks staged into rounds
        self.useful_tokens = 0
        self.wasted_tokens = 0          # partial tokens of non-ok rows
        self.prefill_seconds = 0.0      # staging wall time (bench lever)
        self.peak_staged = 0            # concurrently staged rows HWM
        self.n_shed: collections.Counter = collections.Counter()
        self.n_timeouts = 0
        self.n_cancelled = 0
        self.n_quarantined = 0
        self.n_drains = 0
        self._draining = False          # epoch persists across reset()

    # ------------------------------------------------------------------ #
    # public API
    # ------------------------------------------------------------------ #

    def warm(self) -> None:
        """Compile the staging programs ahead of serving: dispatch the
        chunk-prefill program (or the monolithic fallback) once with
        an all-invalid scatter — every block write is dropped, so the
        pool content round-trips unchanged — and, when a draft model
        is attached, the draft-prefill program at an out-of-range
        slot.  The round programs compile on their first natural use;
        staging is the one program whose first compile would otherwise
        land inside a latency-sensitive admit window.  (The rebase
        prewarm this replaces is gone with the rebase program itself:
        ragged rows never share a horizon, so nothing ever shifts.)"""
        row = np.zeros((self._pq,), np.int32)
        if self._can_suffix:
            nw = self._chunk_tokens // self.block + 1
            self._pools = self._chunk_prefill_fn(
                self._params, self._pools,
                np.zeros((self._pq + self._chunk_tokens,), np.int32),
                np.zeros((self._chunk_tokens,), np.int32),
                np.int32(0), np.full((nw,), -1, np.int32),
                np.zeros((nw,), bool))
        else:
            self._pools = self._prefill_fn(
                self._params, self._pools, row,
                np.full((self._w,), -1, np.int32),
                np.zeros((self._w,), bool))
        if self.draft_adapter is not None:
            self._draft_caches = self._draft_prefill_fn(
                self._draft_params, self._draft_caches, row,
                np.int32(-1))

    def mark_steady(self) -> None:
        """Declare this engine's programs steady-state in the program
        ledger: the caller asserts warmup traffic has compiled every
        program it intends to serve with, so any further ``serve/*``
        compile is a retrace-storm signal (``compile/
        steady_retraces``, the ``retrace_storm_rule`` feed).  Call
        after the warmup pass; a deliberate rebuild (resize, engine
        swap) should ``get_ledger().forget("serve/")`` — the rebuilt
        programs are new executables, so their compiles must be
        re-recorded even at previously-seen signatures — then
        re-warm and re-mark.  (Not automatic on construction:
        coexisting engines legitimately share these labels, and a
        second engine's construction must not invalidate the first's
        recorded programs.)  A colocated
        :class:`~chainermn_tpu.serving.SpeculativeDecoder` has its
        own ``mark_steady`` for its ``spec/`` scope — this one covers
        ``serve/`` only."""
        get_ledger().mark_steady("serve/")

    def register_memory(self, accountant=None,
                        prefix: str = "serving") -> None:
        """Register this engine's device-buffer roots with the memory
        accountant: ``<prefix>_params``, ``<prefix>_caches`` (the
        per-slot KV lanes + token buffer), ``<prefix>_pool`` (the
        block-paged staging pool — the prefix cache lives inside it).
        Roots are held via weakref (``programs.weakref_root``), so
        registration never pins a retired engine; a dead root samples
        as 0 bytes."""
        acc = accountant if accountant is not None else get_accountant()
        acc.register(f"{prefix}_params", weakref_root(self, "_params"))
        acc.register(f"{prefix}_caches",
                     weakref_root(self, "_caches", "_buf"))
        acc.register(f"{prefix}_pool", weakref_root(self, "_pools"))

    def set_policy(self, policy: Union[str, Callable]) -> None:
        """Swap the admission policy (host-side only — no recompile)."""
        if callable(policy):
            self._policy = policy
        elif policy in _POLICIES:
            self._policy = _POLICIES[policy]
        else:
            raise ValueError(
                f"policy {policy!r} not in {sorted(_POLICIES)} and not "
                "callable")

    def submit(self, prompt, max_new: Optional[int] = None,
               request_id: Optional[str] = None, *,
               priority: int = 0, tenant: Optional[str] = None,
               deadline: Optional[float] = None,
               timeout: Optional[float] = None,
               epoch: Optional[int] = None,
               trace_id: Optional[str] = None,
               sampling: Optional[SamplingParams] = None
               ) -> Union[str, ShedCompletion]:
        """Queue one request; returns its id — or, when the attached
        admission controller rejects it (queue full, tenant over
        quota, deadline predicted unmeetable), the reason-coded
        :class:`ShedCompletion` instead of letting it age in the
        queue.  The reject is also appended to
        :meth:`request_records` and counted in ``serve/shed_*``.

        ``deadline`` is an absolute ``time.perf_counter`` timestamp;
        ``timeout`` is the relative convenience form (seconds from
        now) — give at most one.  ``priority`` is
        smaller-is-more-important (class 0 beats class 1).

        ``epoch`` (optional) is the serving epoch the CALLER believes
        is current: a mismatch with :attr:`epoch` is shed
        ``"stale_epoch"`` — a front-end that slept through a resize
        must re-learn the world, not have its request served under
        assumptions that moved.  While :meth:`drain` is in progress
        every submit is shed ``"draining"`` with the predicted
        ``retry_after``.

        ``trace_id`` propagates a caller-side causal-trace identity
        (a distributed-tracing id from the front-end); with request
        tracing enabled (``traces=``) one is generated when absent.
        It becomes the exemplar on every ``serve/*`` histogram
        observation this request feeds and names its retained
        timeline in ``engine.traces``.

        ``sampling`` (a
        :class:`~chainermn_tpu.serving.sampling.SamplingParams`)
        switches THIS request to keyed temperature/top-k/top-p
        sampling; ``None`` keeps the greedy path — the exactness
        oracle — even when sampled requests share its rounds.  A
        sampled request replays bit-identically from its
        ``(seed, params, prompt)`` under any scheduling."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not 1 <= prompt.shape[0] <= self.max_prompt:
            raise ValueError(
                f"prompt length {prompt.shape[0]} not in "
                f"[1, {self.max_prompt}]")
        max_new = self.default_max_new if max_new is None else int(max_new)
        if not 1 <= max_new <= self.horizon - self._pq:
            raise ValueError(
                f"max_new={max_new} not in [1, horizon - padded prompt "
                f"= {self.horizon - self._pq}]")
        now = time.perf_counter()
        if timeout is not None:
            if deadline is not None:
                raise ValueError("give deadline= OR timeout=, not both")
            if timeout <= 0:
                raise ValueError(f"timeout={timeout} must be > 0")
            deadline = now + timeout
        if request_id is None:
            request_id = f"r{self._next_rid}"
            self._next_rid += 1
        if any(r.rid == request_id for r in self._queue) \
                or any(r is not None and r.rid == request_id
                       for r in self._slot_req):
            raise ValueError(f"request id {request_id!r} already live")
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise ValueError(
                f"sampling= takes a SamplingParams, got "
                f"{type(sampling).__name__}")
        req = Request(request_id, prompt, max_new, t_submit=now,
                      priority=int(priority), tenant=tenant,
                      deadline=deadline, sampling=sampling)
        if self.traces is not None:
            req.trace_id = (str(trace_id) if trace_id is not None
                            else uuid.uuid4().hex[:16])
            req.spans = []
        elif trace_id is not None:
            # no retention, but the identity still rides the records
            # and exemplars — a front-end's trace id is never dropped
            req.trace_id = str(trace_id)
        reg = get_registry()
        # serve/submitted counts the SCORED request stream — it is
        # the burn-rate rules' total feed, so protective "overload"
        # sheds (excluded from serve/shed_total below for the same
        # reason) must not dilute it either: counting them as
        # zero-bad traffic would drive the bad fraction down and
        # self-extinguish the alert mid-burst (protection flapping at
        # the short-window period).  It is incremented on every path
        # out of this method EXCEPT the overload shed.
        if self._draining:
            reg.inc("serve/submitted")
            # checked FIRST: during the handover window a front-end
            # that already learned the NEW epoch is early, not wrong —
            # it gets the transient "draining" + retry_after, never the
            # terminal re-learn-the-world verdict below
            return self._finish_shed(req, "draining",
                                     retry_after=self._retry_after())
        if epoch is not None and int(epoch) != self.epoch:
            reg.inc("serve/submitted")
            if int(epoch) < self.epoch:
                return self._finish_shed(
                    req, "stale_epoch",
                    detail=f"submit epoch {int(epoch)} vs engine epoch "
                           f"{self.epoch}")
            # a NEWER epoch: the ENGINE is the stale party (its
            # complete_drain hasn't run yet) — transient, retry
            return self._finish_shed(
                req, "draining", retry_after=self._retry_after(),
                detail=f"engine epoch {self.epoch} behind submit epoch "
                       f"{int(epoch)}")
        if self.admission is not None:
            admit, reason, victim = self.admission.check_submit(
                req, list(self._queue), self._tenant_tokens,
                n_slots=self.n_slots,
                ahead_tokens=self._ahead_tokens(req))
            if victim is not None:
                # a lower-priority queued request makes room; its shed
                # record flows out of the next step()
                self._shed_from_queue(victim, "queue_full",
                                      detail=f"displaced by {req.rid}")
            if not admit:
                # transient rejects carry a come-back hint, each from
                # its own clock: queue_full drains with the backlog
                # (predictor estimate), over_quota with the TENANT's
                # own in-flight drain (how long until enough of its
                # budget retires for this request to fit), and an
                # "overload" protective shed resolves with the
                # burn-rate alert's window (the operator-configured
                # hint — the backlog estimate would read ~0 off an
                # empty queue and invite a retry storm
                # mid-protection).  Only deadline is a terminal
                # verdict with no clock at all.
                if reason == "queue_full":
                    after = self._retry_after()
                elif reason == "over_quota":
                    after = self._quota_retry_after(req)
                elif reason == "overload":
                    after = self.admission.overload_retry_after
                else:
                    after = None
                if reason != "overload":
                    reg.inc("serve/submitted")
                return self._finish_shed(req, reason,
                                         retry_after=after)
        reg.inc("serve/submitted")
        self._queue.append(req)
        self._tenant_tokens[tenant] += max_new
        self._charged.add(request_id)
        get_recorder().counter("serve/queue_depth", len(self._queue),
                               cat="serve")
        reg.set("serve/queue_depth", len(self._queue))
        return request_id

    def cancel(self, request_id: str) -> bool:
        """Cancel a live request: a queued copy is drained (staged
        blocks freed, a ``ShedCompletion(reason="cancelled")`` flows
        out of the next :meth:`step`); an ACTIVE row is evicted on the
        next step with its partial tokens and
        ``status="cancelled"`` — the slot frees immediately after.
        Returns False when the id is not live (already completed,
        shed, or never submitted) — cancellation races are normal, not
        errors."""
        for req in list(self._queue):
            if req.rid == request_id:
                self._shed_from_queue(req, "cancelled")
                return True
        for s in range(self.n_slots):
            req = self._slot_req[s]
            if req is not None and req.rid == request_id:
                if self._done[s]:
                    # already finished (or already timed out /
                    # quarantined), just awaiting eviction — too late
                    # to cancel; don't relabel a served completion
                    return False
                self._done[s] = True
                self._slot_status[s] = "cancelled"
                return True
        return False

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self._slot_req)

    @property
    def idle(self) -> bool:
        return (not self._queue and self.n_active == 0
                and not self._pending_shed)

    def step(self) -> List[Union[Completion, ShedCompletion]]:
        """One scheduler iteration: evict finished/expired rows, admit
        from the queue (shedding what can no longer make its
        deadline), run one decode round.  Returns this iteration's
        terminal records — served :class:`Completion`\\ s (``status``
        ``"ok"`` or a mid-stream ``"timeout"`` / ``"cancelled"`` /
        ``"quarantined"``) and queue-side :class:`ShedCompletion`\\ s.

        A decode-round failure does NOT crash the engine: the
        newest-admitted live request is quarantined (evicted next
        step with ``status="quarantined"``) and the remaining slots
        keep serving — unless the failure consumed the round's donated
        buffers, in which case the device state is gone and a
        ``RuntimeError`` propagates."""
        rec = get_recorder()
        out: List[Union[Completion, ShedCompletion]] = []
        self._evict_phase(out, rec)
        self._admit_phase(rec)
        if self._pending_shed:          # queue sheds from this tick
            out.extend(self._pending_shed)
            self._pending_shed.clear()
        n_live = sum(1 for s in range(self.n_slots)
                     if self._slot_req[s] is not None
                     and not self._done[s])
        if n_live:
            rt0 = time.perf_counter()
            spec = (self.draft_adapter is not None
                    and not self._n_sampled_active)
            cap = (self.spec_k + 1) if spec else self.round_tokens
            try:
                with rec.span("serve/decode_round", cat="serve",
                              step=int(self.n_rounds),
                              tokens=cap, active=self.n_active):
                    if self._n_sampled_active:
                        # keyed-sampling round; greedy rows inside it
                        # still take the argmax values.  The sampling
                        # arrays are rewritten per admission, so the
                        # jitted call gets copies (the staging-buffer
                        # aliasing discipline)
                        self._caches, self._buf, pos_dev, done_dev = \
                            self._round_sampled_fn(
                                self._params, self._caches, self._buf,
                                self._staging_copy(self._pos),
                                self._staging_copy(self._done),
                                self._staging_copy(self._end),
                                self._staging_copy(self._s_temp),
                                self._staging_copy(self._s_topk),
                                self._staging_copy(self._s_topp),
                                self._staging_copy(self._s_keys))
                    elif spec:
                        # speculative round MODE: per-row draft/verify
                        # with ragged accepted-token counts.  Sampled
                        # rows force the per-token fallback above —
                        # spec acceptance is defined against the
                        # target's argmax
                        (self._caches, self._draft_caches, self._buf,
                         pos_dev, done_dev, acc_dev, com_dev) = \
                            self._round_spec_fn(
                                self._params, self._draft_params,
                                self._caches, self._draft_caches,
                                self._buf,
                                self._staging_copy(self._pos),
                                self._staging_copy(self._done),
                                self._staging_copy(self._end))
                        drafted = self.spec_k * n_live
                        accepted = int(np.sum(np.array(acc_dev)))
                        self.spec_drafted += drafted
                        self.spec_accepted += accepted
                        reg0 = get_registry()
                        reg0.inc("serve/spec_drafted", drafted)
                        reg0.inc("serve/spec_accepted", accepted)
                    else:
                        # all-greedy per-token rounds
                        self._caches, self._buf, pos_dev, done_dev = \
                            self._round_fn(
                                self._params, self._caches, self._buf,
                                self._staging_copy(self._pos),
                                self._staging_copy(self._done),
                                self._staging_copy(self._end))
                    # np.array, not asarray: the host mirrors are
                    # mutated by admissions, and jax arrays view out
                    # read-only
                    self._pos = np.array(pos_dev)
                    self._done = np.array(done_dev)  # the round's sync
            except Exception as err:        # noqa: BLE001 — harden
                self._on_round_failure(err, rec)
            else:
                self.n_rounds += 1
                self._round_capacity += cap * self.n_slots
                now = time.perf_counter()
                if self.traces is not None:
                    # per-round spans are SAMPLED into request
                    # timelines (every Nth round), except a request's
                    # first round — the TTFT cause is always on its
                    # trace
                    sampled = (self.n_rounds
                               % self.trace_decode_every == 0)
                    for s in range(self.n_slots):
                        r = self._slot_req[s]
                        if r is None or r.spans is None:
                            continue
                        if sampled or s in self._pending_first:
                            self._rspan(r, "decode_round", rt0,
                                        now - rt0,
                                        round=self.n_rounds,
                                        tokens=cap)
                reg = get_registry()
                for s in self._pending_first:
                    req = self._slot_req[s]
                    req.t_first = now
                    # TTFT lands here — the first moment the request's
                    # first generated token is host-observable
                    reg.observe("serve/ttft", now - req.t_submit,
                                exemplar=req.trace_id)
                    if self.admission is not None:
                        self.admission.predictor.observe_ttft(
                            now - req.t_submit)
                        if req.t_admit is not None:
                            # queue-free service TTFT: admit -> first
                            # token, the predictor's service-side
                            # evidence (wait is predicted separately)
                            self.admission.predictor \
                                .observe_service_ttft(now - req.t_admit)
                self._pending_first.clear()
        rec.counter("serve/active_slots", self.n_active, cat="serve")
        return out

    def _on_round_failure(self, err, rec) -> None:
        """Quarantine-and-continue: the shared decode round cannot
        attribute a failure to one row, so the NEWEST-admitted live
        request (the thing that most recently changed the batch) is
        evicted ``status="quarantined"`` and the round retries next
        step with the remaining rows.  A persistent fault therefore
        drains the batch one quarantine per step — degraded, never
        hung.  If the failure consumed the round's donated buffers the
        device state is unrecoverable and the error propagates."""
        state = (self._caches, self._buf)
        if self.draft_adapter is not None:
            state = state + (self._draft_caches,)
        for leaf in jax.tree.leaves(state):
            if getattr(leaf, "is_deleted", lambda: False)():
                raise RuntimeError(
                    "decode round failed after its donated buffers "
                    "were consumed — engine state is lost; reset() "
                    "and resubmit") from err
        live = [s for s in range(self.n_slots)
                if self._slot_req[s] is not None and not self._done[s]]
        victim = max(live,
                     key=lambda s: (self._slot_req[s].t_admit or 0.0, s))
        self._done[victim] = True
        self._slot_status[victim] = "quarantined"
        self._slot_detail[victim] = f"{type(err).__name__}: {err}"
        rec.counter("serve/round_failures", 1, cat="serve")
        get_registry().inc("serve/round_failures")

    def run(self, max_steps: Optional[int] = None) -> List[Completion]:
        """Drive :meth:`step` until queue and slots drain."""
        out: List[Completion] = []
        steps = 0
        while not self.idle:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    # ------------------------------------------------------------------ #
    # epoch drains (docs/SERVING.md "Epoch drains")
    # ------------------------------------------------------------------ #

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, *, timeout: Optional[float] = None,
              max_steps: Optional[int] = None
              ) -> List[Union[Completion, ShedCompletion]]:
        """Retire every ACTIVE row ahead of an epoch change (a live
        resize, a rolling restart) without restarting the fleet:

        - admission STOPS — queued requests hold their place, every new
          submit is shed ``"draining"`` with the predictor's
          ``retry_after`` estimate;
        - active rows finishing naturally complete ``"ok"``; with
          ``timeout`` the rest are timeout-evicted at the deadline with
          their partial tokens (a verified PREFIX of the solo decode —
          the engine's ordinary mid-stream eviction);
        - decode rounds keep running until the slots are empty, then
          this returns the terminal records produced along the way.

        The engine stays in drain mode afterwards;
        :meth:`complete_drain` re-opens admission under the new epoch
        (typically after ``ResizeController`` re-formed the world).
        ``max_steps`` bounds the loop for drills."""
        if timeout is not None and timeout <= 0:
            raise ValueError(f"timeout={timeout} must be > 0")
        self._draining = True
        self.n_drains += 1
        get_registry().inc("serve/drains")
        if timeout is not None:
            dl = time.perf_counter() + timeout
            for s in range(self.n_slots):
                req = self._slot_req[s]
                if req is not None and not self._done[s]:
                    req.deadline = dl if req.deadline is None \
                        else min(req.deadline, dl)
        out: List[Union[Completion, ShedCompletion]] = []
        steps = 0
        with get_recorder().span("serve/drain", cat="serve",
                                 active=self.n_active,
                                 queued=len(self._queue)):
            while self.n_active:
                out.extend(self.step())
                steps += 1
                if max_steps is not None and steps >= max_steps:
                    break
        return out

    def complete_drain(self, epoch: Optional[int] = None) -> None:
        """Re-open admission after a :meth:`drain`, optionally bumping
        to the NEW serving epoch (the agreed membership epoch).  Queued
        requests kept their place and admit normally from the next
        :meth:`step`; epochs only move forward."""
        if epoch is not None:
            if int(epoch) < self.epoch:
                raise ValueError(
                    f"epoch={epoch} would move backwards (engine is at "
                    f"{self.epoch}) — epochs only advance")
            self.epoch = int(epoch)
        self._draining = False

    def export_queue(self) -> List[Request]:
        """Remove and return every QUEUED request (submit order,
        timestamps intact) — the carry-over half of surviving a resize:
        drain the old engine, export its queue, and
        :meth:`import_queue` into the engine rebuilt for the new world
        so waiting requests keep their place instead of being shed.
        Staged pool blocks are freed (the new engine re-prefills
        against its own pool)."""
        reqs = list(self._queue)
        for r in reqs:
            self._staged.pop(r.rid, None)
            self._chunking.pop(r.rid, None)
            self._alloc.free_row(r.rid)
            self._release_tokens(r)
        self._queue.clear()
        get_recorder().counter("serve/queue_depth", 0, cat="serve")
        get_registry().set("serve/queue_depth", 0)
        return reqs

    def import_queue(self, reqs: Sequence[Request]) -> None:
        """Adopt requests exported from another engine (see
        :meth:`export_queue`); submit order and ``t_submit`` are
        preserved so queue-wait metrics stay honest across the
        handover.

        All-or-nothing: every rid is validated against this engine's
        live set BEFORE anything is adopted, so a collision raises
        with the queue untouched — a failover caller can fall back to
        per-request re-dispatch without first unwinding a partial
        import."""
        live = {q.rid for q in self._queue}
        live.update(a.rid for a in self._slot_req if a is not None)
        for r in reqs:
            if r.rid in live:
                raise ValueError(f"request id {r.rid!r} already live")
            live.add(r.rid)
        for r in reqs:
            self._queue.append(r)
            self._tenant_tokens[r.tenant] += r.max_new
            self._charged.add(r.rid)
            # auto-assigned rids ("r<n>") from the old engine share this
            # engine's namespace: advance the counter past them, or the
            # n-th native submit regenerates an imported rid and raises
            # "already live" at an ordinary caller
            m = re.fullmatch(r"r(\d+)", r.rid)
            if m:
                self._next_rid = max(self._next_rid,
                                     int(m.group(1)) + 1)
        get_recorder().counter("serve/queue_depth", len(self._queue),
                               cat="serve")
        get_registry().set("serve/queue_depth", len(self._queue))

    def import_prefixes(self, prefixes: Sequence[np.ndarray]) -> int:
        """Warm the prefix cache with token prefixes exported from
        another engine (see
        :func:`~chainermn_tpu.serving.prefix_cache.prefix_snapshot`) —
        the rejoin half of a fleet failover: a restarted replica
        re-prefills the snapshot's prefixes ONCE (as ordinary 1-token
        requests, paying compute but no retrace) so subsequent traffic
        hits its cache and the router's prefix-placement signal
        survives the restart.  Must be called idle; returns the number
        of newly cached blocks.

        Prefixes that don't fit (shorter than one full block after
        clipping to ``max_prompt``) or are already cached are
        skipped — importing is best-effort by design."""
        if not self.idle:
            raise ValueError("import_prefixes needs an idle engine")
        before = self._alloc.n_cached
        warmed = 0
        for i, p in enumerate(prefixes):
            p = np.asarray(p, np.int32).reshape(-1)
            end = min(int(p.shape[0]), self.max_prompt - 1)
            end = (end // self.block) * self.block
            if end < self.block:
                continue
            p = p[:end]
            if len(self._alloc._trie.lookup_run(p)) * self.block \
                    >= end:
                continue
            res = self.submit(p, max_new=1,
                              request_id=f"__warm{i}__")
            if isinstance(res, ShedCompletion):
                continue
            warmed += 1
        if warmed:
            self.run()
        return self._alloc.n_cached - before

    def stats(self) -> dict:
        issued = self._round_capacity
        out = {
            "rounds": self.n_rounds,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "chunk_prefills": self.n_chunk_prefills,
            "useful_tokens": self.useful_tokens,
            "wasted_tokens": self.wasted_tokens,
            "slot_utilization": (self.useful_tokens / issued
                                 if issued else 0.0),
            "pool_utilization": self._alloc.utilization,
            "queue_depth": len(self._queue),
            "shed": dict(self.n_shed),
            "timeouts": self.n_timeouts,
            "cancelled": self.n_cancelled,
            "quarantined": self.n_quarantined,
            "epoch": self.epoch,
            "draining": self._draining,
            "drains": self.n_drains,
            "prefill_seconds": self.prefill_seconds,
            "peak_staged": self.peak_staged,
        }
        out.update(self._alloc.stats())    # prefix_* / peak_blocks_used
        return out

    def request_records(self) -> List[Completion]:
        """The newest completed requests (up to ``record_history``,
        oldest dropped; cleared by :meth:`reset`), in eviction order —
        the :class:`Completion` the engine already built at eviction,
        with the derived ``queue_wait`` / ``ttft`` / ``tpot`` /
        ``e2e`` latency fields, so SLO consumers (``SLOReport``,
        ``bench_serving``) never recompute them."""
        return list(self._records)

    def metrics_snapshot(self) -> dict:
        """The ``serve/*`` slice of the global metrics registry —
        per-request queue-wait/TTFT/TPOT/e2e histograms plus
        submit/admit/evict counters recorded at the points that
        hold the timestamps.  Empty when the registry is disabled
        (``CHAINERMN_TPU_METRICS=1`` or
        ``utils.metrics.get_registry().enable()`` turn it on);
        :meth:`request_records` is the always-on per-request form."""
        return get_registry().snapshot(prefix="serve/")

    # ------------------------------------------------------------------ #
    # request-scoped tracing (docs/OBSERVABILITY.md "Request tracing")
    # ------------------------------------------------------------------ #

    def _rspan(self, req: Request, name: str, t0: float, dur: float,
               **meta) -> None:
        """Append one span to a TRACED request's timeline.  Untraced
        requests (``spans is None`` — tracing off) fall through the
        first check with zero allocations."""
        if req.spans is None:
            return
        span = {"name": name, "t0": t0, "dur": dur}
        if meta:
            span.update(meta)
        req.spans.append(span)

    def _offer_trace(self, req: Request, comp) -> None:
        """Hand a finished request's timeline to the trace store —
        tail-based retention there decides whether it survives
        (non-ok and SLO-violating always, ok sampled)."""
        if req.spans is None or self.traces is None:
            return
        trace = {
            "trace_id": req.trace_id,
            "rid": req.rid,
            "status": comp.status,
            "queue_wait": getattr(comp, "queue_wait", None),
            "ttft": getattr(comp, "ttft", None),
            "e2e": getattr(comp, "e2e", None),
            "n_generated": comp.n_generated,
            "spans": req.spans,
        }
        reason = getattr(comp, "reason", None)
        if reason is not None:
            trace["reason"] = reason
        self.traces.offer(trace)

    # ------------------------------------------------------------------ #
    # phases
    # ------------------------------------------------------------------ #

    def _evict_phase(self, out: List[Completion], rec) -> None:
        now = time.perf_counter()
        for s in range(self.n_slots):
            req = self._slot_req[s]
            if req is None:
                continue
            if (not self._done[s] and req.deadline is not None
                    and now >= req.deadline):
                # deadline expired MID-stream: evict with the partial
                # tokens rather than burn more rounds on a miss
                self._done[s] = True
                self._slot_status[s] = "timeout"
            if not self._done[s]:
                continue
            status = self._slot_status[s]
            detail = self._slot_detail[s]
            et0 = time.perf_counter()
            with rec.span("serve/evict", cat="serve", rid=req.rid,
                          slot=s, status=status):
                row = np.asarray(self._buf[s])
                # origin-0 lane: generated tokens live at positions
                # [plen, pos]; a mid-stream eviction (timeout/cancel/
                # quarantine) has only decoded up to the row's OWN
                # position, which is all the clock there is
                gen = row[int(self._plen[s]): int(self._pos[s]) + 1]
                if self.eos_id >= 0:
                    hits = np.nonzero(gen == self.eos_id)[0]
                    if hits.size:
                        gen = gen[:int(hits[0]) + 1]
                self._slot_req[s] = None
                self._pos[s] = 0
                self._plen[s] = 0
                self._end[s] = 0
                if req.sampling is not None:
                    self._s_temp[s] = 0.0
                    self._s_topk[s] = 0
                    self._s_topp[s] = 1.0
                    self._s_keys[s] = 0
                    self._n_sampled_active -= 1
                self._slot_status[s] = "ok"
                self._slot_detail[s] = ""
                self._pending_first.discard(s)
                if status == "ok":
                    self.useful_tokens += int(gen.shape[0])
                else:
                    self.wasted_tokens += int(gen.shape[0])
            comp = Completion(
                rid=req.rid, prompt=req.prompt, tokens=np.array(gen),
                t_submit=req.t_submit, t_admit=req.t_admit,
                t_first=req.t_first, t_done=time.perf_counter(),
                slot=s, status=status, detail=detail,
                trace_id=req.trace_id)
            self._release_tokens(req)
            self._records.append(comp)
            if req.spans is not None:
                if status != "ok":
                    # the terminal cause gets its own mark on the
                    # timeline (the span a "why did this time out"
                    # reader looks for first)
                    self._rspan(req, status, comp.t_done, 0.0,
                                **({"detail": detail} if detail
                                   else {}))
                self._rspan(req, "evict", et0, comp.t_done - et0,
                            slot=s, status=status,
                            tokens=comp.n_generated)
                self._offer_trace(req, comp)
            reg = get_registry()
            reg.inc("serve/evictions")
            reg.inc("serve/generated_tokens", comp.n_generated)
            if status == "ok":
                # only fully-served rows feed the latency
                # distributions — a truncated timeout row would bias
                # the predictor (and the dashboard) optimistic
                reg.observe("serve/tpot", comp.tpot,
                            exemplar=req.trace_id)
                reg.observe("serve/e2e", comp.e2e,
                            exemplar=req.trace_id)
                if self.admission is not None:
                    self.admission.predictor.observe_tpot(comp.tpot)
            elif status == "timeout":
                self.n_timeouts += 1
                reg.inc("serve/timeouts")
            elif status == "cancelled":
                self.n_cancelled += 1
                reg.inc("serve/cancelled")
            elif status == "quarantined":
                self.n_quarantined += 1
                reg.inc("serve/quarantined")
            out.append(comp)

    def _release_tokens(self, req: Request) -> None:
        if req.rid in self._charged:
            self._charged.discard(req.rid)
            self._tenant_tokens[req.tenant] -= req.max_new
            if self._tenant_tokens[req.tenant] <= 0:
                del self._tenant_tokens[req.tenant]

    def _backlog_tokens(self) -> int:
        """The live token backlog a capacity shed quotes: queued
        budgets plus active rows' remaining budgets."""
        backlog = sum(r.max_new for r in self._queue)
        for s in range(self.n_slots):
            if self._slot_req[s] is not None and not self._done[s]:
                backlog += max(int(self._end[s]) - int(self._pos[s]),
                               0)
        return backlog

    def _ahead_tokens(self, req: Request) -> Optional[int]:
        """Queued token budget the ADMISSION POLICY would serve before
        ``req`` — the deadline feasibility check's honest wait basis.

        The controller's predictor used to charge every arrival the
        WHOLE queue's drain; under any policy that can serve the new
        request early (deadline slack, short prompt, priority) that
        over-states its wait and sheds feasible requests — observed as
        ``--max-queue 0`` traffic shedding "deadline" off a backlog it
        would never stand behind.  This conditions the wait on the
        request's predicted queue POSITION: sum only requests the
        policy ranks ahead of it.  FCFS keeps the whole queue
        (position = tail); a custom callable policy returns ``None``
        (unknown ordering — fall back to the conservative whole-queue
        charge)."""
        if self._policy is _fcfs:
            return sum(int(r.max_new) for r in self._queue)
        if self._policy is _spf:
            plen = int(req.prompt.shape[0])
            return sum(int(r.max_new) for r in self._queue
                       if int(r.prompt.shape[0]) <= plen)
        if self._policy is _deadline:
            now = time.perf_counter()
            ctrl = self.admission
            pred = ctrl.predictor if ctrl is not None else None

            def key(i, r):
                if r.deadline is None:
                    return (r.priority, 1, 0.0, i)
                rem = pred.predict_remaining(r.max_new) \
                    if pred is not None else None
                slack = (r.deadline - now) \
                    - (rem if rem is not None else 0.0)
                return (r.priority, 0, slack, i)

            mine = key(len(self._queue), req)
            return sum(int(r.max_new)
                       for i, r in enumerate(self._queue)
                       if key(i, r) < mine)
        if self._policy is _wfq:
            return sum(int(r.max_new) for r in self._queue
                       if int(r.priority) <= int(req.priority))
        return None

    def _retry_after(self) -> Optional[float]:
        """Predicted seconds until the current backlog drains (the
        retry-after a capacity shed carries); ``None`` without an
        admission controller or while its predictor is cold."""
        if self.admission is None:
            return None
        return self.admission.retry_after(self._backlog_tokens(),
                                          self.n_slots)

    def _quota_retry_after(self, req: Request) -> Optional[float]:
        """The quota shed's come-back hint: predicted seconds until
        enough of the TENANT's in-flight budget drains for this
        request to fit under its quota.  The drain rate is the pool's
        aggregate (``n_slots / TPOT``) — an upper bound on how fast
        the tenant's own rows can retire, so the hint errs early, not
        late.  ``None`` while the predictor is cold."""
        if self.admission is None:
            return None
        quota = self.admission.quota_for(req.tenant)
        if quota is None:
            return None
        over = self._tenant_tokens[req.tenant] + req.max_new - quota
        if over <= 0:
            return None
        return self.admission.retry_after(int(over), self.n_slots)

    def _finish_shed(self, req: Request, reason: str,
                     detail: str = "",
                     retry_after: Optional[float] = None
                     ) -> ShedCompletion:
        """Terminal bookkeeping for a request that will never be
        served: tenant tokens released, record appended, metrics
        counted.  Returns the typed reject."""
        self._release_tokens(req)
        shed = ShedCompletion(
            rid=req.rid, prompt=req.prompt, reason=reason,
            t_submit=req.t_submit, t_shed=time.perf_counter(),
            max_new=req.max_new, priority=req.priority,
            tenant=req.tenant, detail=detail, retry_after=retry_after,
            trace_id=req.trace_id)
        if req.spans is not None:
            self._rspan(req, "queue_wait", req.t_submit,
                        shed.t_shed - req.t_submit)
            self._rspan(req, "shed", shed.t_shed, 0.0, reason=reason,
                        **({"detail": detail} if detail else {}))
            self._offer_trace(req, shed)
        self._records.append(shed)
        self.n_shed[reason] += 1
        reg = get_registry()
        # the taxonomy is DISJOINT: queue-side terminations count in
        # serve/shed_<reason> only; serve/timeouts / serve/cancelled /
        # serve/quarantined count mid-stream evictions only — their
        # sum with serve/shed_total is every unserved request once.
        # Protective "overload" sheds are EXCLUDED from shed_total:
        # that counter is the burn-rate rules' documented bad feed,
        # and counting the alert's own deliberate sheds into it would
        # make the alert self-sustaining (below-tier traffic keeps
        # arriving → keeps being shed → keeps burning the budget),
        # never auto-resolving after the real cause stops
        if reason != "overload":
            reg.inc("serve/shed_total")
        reg.inc("serve/shed_" + reason)
        return shed

    def _shed_from_queue(self, req: Request, reason: str,
                         detail: str = "") -> ShedCompletion:
        self._queue.remove(req)
        self._staged.pop(req.rid, None)
        self._chunking.pop(req.rid, None)
        self._alloc.free_row(req.rid)
        shed = self._finish_shed(
            req, reason, detail,
            retry_after=(self._retry_after()
                         if reason == "queue_full" else None))
        self._pending_shed.append(shed)
        get_recorder().counter("serve/queue_depth", len(self._queue),
                               cat="serve")
        get_registry().set("serve/queue_depth", len(self._queue))
        return shed

    def _pick(self) -> Request:
        req = self._policy(list(self._queue), self)
        if req not in self._queue:
            raise ValueError(
                f"policy returned a request not in the queue: {req!r}")
        return req

    def _scan_queue_deadlines(self) -> None:
        """Shed queued requests that expired (``"timeout"``) or — with
        an admission controller — can no longer meet their deadline
        per the live prediction (``"deadline"``), instead of letting
        them age in the queue."""
        if not self._queue:
            return
        now = time.perf_counter()
        for req in list(self._queue):
            reason = None
            if req.deadline is not None and now >= req.deadline:
                reason = "timeout"
            elif self.admission is not None:
                reason = self.admission.check_queued(req, now)
            if reason is not None:
                self._shed_from_queue(req, reason)

    def _admit_phase(self, rec) -> None:
        self._scan_queue_deadlines()
        if self._draining:
            # drain mode: no admissions, no speculative prefill — the
            # queue holds (deadlines above still enforced) until
            # complete_drain() re-opens under the new epoch
            return
        # idle is judged ONCE, at phase start: rows admitted later in
        # this same phase have not decoded yet, so synchronous staging
        # while idle delays nothing — and keeps gang batches forming
        # whole and cold-start admission in strict policy order
        idle = not any(self._slot_req[s] is not None
                       and not self._done[s]
                       for s in range(self.n_slots))
        # advance in-flight chunked stagings FIRST, in queue order:
        # one chunk each per round while decode rows are live (the
        # long prompt pays its own staging across rounds), straight
        # to completion when the device would otherwise sit idle
        self._advance_chunks(rec, all_chunks=idle)
        free = [s for s in range(self.n_slots)
                if self._slot_req[s] is None]
        if self.gang and len(free) < self.n_slots:
            free = []                   # static batching: whole gang only
        skip: set = set()
        while free and self._queue:
            cands = [r for r in self._queue if r.rid not in skip]
            if not cands:
                break
            req = self._policy(cands, self)
            if req not in self._queue:
                raise ValueError(
                    "policy returned a request not in the queue: "
                    f"{req!r}")
            try:
                staged = self._ensure_staged(req, rec, idle=idle)
            except Exception as err:    # noqa: BLE001 — harden
                # prefill failed for THIS request: quarantine it and
                # keep admitting others — one poison prompt must not
                # stall the queue (_shed_from_queue frees its blocks)
                self._check_state_alive(err)
                self._shed_from_queue(
                    req, "quarantined",
                    detail=f"stage: {type(err).__name__}: {err}")
                continue
            if staged == "pool_full":
                break                   # pool full until slots drain
            if staged == "chunking":
                # mid-chunking: later-queued requests must not wait
                # behind its remaining chunks (TTFT independence) —
                # skip it and keep admitting
                skip.add(req.rid)
                continue
            slot = free.pop(0)
            self._queue.remove(req)
            at0 = time.perf_counter()
            try:
                with rec.span("serve/admit", cat="serve", rid=req.rid,
                              slot=slot):
                    flat, prompt_row = self._staged.pop(req.rid)
                    self._caches, self._buf = self._admit_fn(
                        self._caches, self._buf, self._pools, flat,
                        prompt_row, np.int32(slot))
                    if self.draft_adapter is not None:
                        # rebuild the slot's draft lane from the
                        # left-aligned prompt row (the draft model has
                        # no staging pool)
                        self._draft_caches = self._draft_prefill_fn(
                            self._draft_params, self._draft_caches,
                            prompt_row, np.int32(slot))
                    # refcount-aware: the row lets go, but blocks the
                    # trie (or other rows) hold stay resident — that
                    # retention IS the prefix cache
                    self._alloc.free_row(req.rid)
            except Exception as err:    # noqa: BLE001 — harden
                self._check_state_alive(err)
                self._alloc.free_row(req.rid)
                self._pending_shed.append(self._finish_shed(
                    req, "quarantined",
                    detail=f"admit: {type(err).__name__}: {err}"))
                free.insert(0, slot)    # the slot was never filled
                continue
            p = int(req.prompt.shape[0])
            self._pos[slot] = p - 1
            self._plen[slot] = p
            # p - 1 + max_new <= Pq - 1 + max_new <= H - 1 by submit
            # validation: a row's end never needs a shared horizon
            self._end[slot] = p - 1 + req.max_new
            self._done[slot] = False
            self._slot_req[slot] = req
            if req.sampling is not None:
                sp = req.sampling
                self._s_temp[slot] = sp.temperature
                self._s_topk[slot] = sp.top_k
                self._s_topp[slot] = sp.top_p
                self._s_keys[slot] = np.asarray(sp.key())
                self._n_sampled_active += 1
            self._pending_first.add(slot)
            req.t_admit = time.perf_counter()
            if self.admission is not None:
                # settle the WFQ pick's token cost only now that the
                # admission actually LANDED (a failed stage leaves the
                # request queued and must not be charged twice)
                self.admission.wfq_charge(req)
            self.admit_log.append(req.rid)
            if req.spans is not None:
                self._rspan(req, "queue_wait", req.t_submit,
                            req.t_admit - req.t_submit)
                self._rspan(req, "admit", at0, req.t_admit - at0,
                            slot=slot)
            rec.counter("serve/queue_depth", len(self._queue),
                        cat="serve")
            reg = get_registry()
            reg.inc("serve/admits")
            reg.observe("serve/queue_wait", req.t_admit - req.t_submit,
                        exemplar=req.trace_id)
            reg.set("serve/queue_depth", len(self._queue))
        if self.prefill_ahead:
            budget = self.prefill_ahead
            for req in list(self._queue):
                if budget <= 0:
                    break
                if req.rid in self._staged \
                        or req.rid in self._chunking:
                    continue
                try:
                    if self._stage_traced(req, rec, steal=False,
                                          idle=idle) == "pool_full":
                        break
                except Exception as err:    # noqa: BLE001 — harden
                    self._check_state_alive(err)
                    self._shed_from_queue(
                        req, "quarantined",
                        detail=f"stage: {type(err).__name__}: {err}")
                    continue
                budget -= 1

    def _check_state_alive(self, err) -> None:
        """Donated-buffer guard for the harden paths: if a failed
        program call consumed its donated inputs, the device state is
        unrecoverable — propagate instead of serving garbage."""
        for leaf in jax.tree.leaves(
                (self._caches, self._buf, self._pools)):
            if getattr(leaf, "is_deleted", lambda: False)():
                raise RuntimeError(
                    "serving program failed after its donated buffers "
                    "were consumed — engine state is lost; reset() "
                    "and resubmit") from err

    # ------------------------------------------------------------------ #
    # staging / paging
    # ------------------------------------------------------------------ #

    def _staging_copy(self, buf: np.ndarray) -> np.ndarray:
        """The one copy the admit path owes: staging buffers are
        rewritten per admission, and a deferred sharded ``device_put``
        may alias host memory without ``block_until_ready`` forcing the
        copy (see ``iterators.prefetch.put_window``)."""
        return np.array(buf)

    def _stage(self, req: Request, rec, steal: bool,
               idle: bool = True) -> str:
        """Begin (and possibly finish) staging ``req``'s prompt into
        pool blocks.  With prefix sharing the cached leading full
        blocks are REFERENCED, a mid-block divergence forks the
        matching sub-block prefix onto a fresh block with a device
        copy (``copy_block`` — no recompute), and only tokens from the
        divergence point on are prefilled.  Prefill runs in
        fixed-shape CHUNKS of ``prefill_chunk`` blocks through the
        adapter's verify surface: with live decode rows the remaining
        chunks interleave one per round (``_advance_chunks``) so a
        long prompt never stalls co-scheduled requests; with the
        device otherwise idle every chunk runs now.  ``steal`` frees
        queue-tail stagings to make room (admission path only;
        prefill-ahead never steals).  Staging is LEFT-aligned — token
        ``i`` in block ``i // block`` — which is both what makes block
        content addressable by token prefix AND the lane layout
        origin-0 rows decode from: admission is a straight gather.

        Returns ``"ready"`` (staged, admission can gather),
        ``"chunking"`` (chunks still in flight), or ``"pool_full"``."""
        P_len = int(req.prompt.shape[0])
        n_real = kvb.blocks_needed(P_len, self.block)
        plan = self._alloc.stage(req.rid, req.prompt)
        while plan is None and steal:
            victims = [r for r in reversed(list(self._queue))
                       if (r.rid in self._staged
                           or r.rid in self._chunking)
                       and r is not req]
            if not victims:
                return "pool_full"
            victim = victims[0]
            self._alloc.free_row(victim.rid)
            self._staged.pop(victim.rid, None)
            self._chunking.pop(victim.rid, None)
            plan = self._alloc.stage(req.rid, req.prompt)
        if plan is None:
            return "pool_full"
        reg = get_registry()
        pt0 = time.perf_counter()
        with rec.span("serve/prefill", cat="serve", rid=req.rid,
                      blocks=plan.n_new, shared=plan.n_shared):
            st = self._lprompt_staging
            st[:] = max(self.pad_id, 0)
            st[:P_len] = req.prompt
            prompt_row = self._staging_copy(st)
            if plan.copy_src is not None:
                # sub-block fork-with-copy: the row diverges MID-block
                # from a cached child — device-copy the whole cached
                # block onto this row's first fresh block and resume
                # prefill at the divergence point, instead of
                # recomputing the matched sub-block prefix
                ft0 = time.perf_counter()
                with rec.span("serve/fork", cat="serve", rid=req.rid,
                              src=int(plan.copy_src),
                              copied=plan.n_copied):
                    self._pools = self._fork_fn(
                        self._pools, np.int32(plan.copy_src),
                        np.int32(plan.table[plan.n_shared]))
                # the transient ref stage() took on the source block
                # (so the steal loop above could not reclaim it before
                # the copy) is released only now
                self._alloc.copy_done(plan.copy_src)
                reg.inc("serve/prefix_forks")
                self._rspan(req, "fork", ft0,
                            time.perf_counter() - ft0,
                            copied=plan.n_copied)
            if plan.n_new and not self._can_suffix:
                # no chunk-attends-cache surface: monolithic prefill
                # of the whole left-aligned row, scatter only this
                # row's fresh blocks (never a shared one)
                ids_np = self._ids_staging
                ids_np[:] = -1
                ids_np[plan.n_shared:n_real] = \
                    plan.table[plan.n_shared:]
                ids_row = self._staging_copy(ids_np)
                self._pools = self._prefill_fn(
                    self._params, self._pools, prompt_row,
                    ids_row, ids_row >= 0)
            elif plan.n_new:
                start = plan.n_shared * self.block + plan.n_copied
                if start < P_len:
                    job = self._build_chunk_job(req, plan, P_len,
                                                n_real, start,
                                                prompt_row)
                    self._chunking[req.rid] = job
            # plan.n_new == 0: the whole prompt is cached full blocks —
            # no prefill compute at all, admission is just the gather
        dur = time.perf_counter() - pt0
        self.prefill_seconds += dur
        if plan.n_shared or plan.n_copied:
            reg.inc("serve/prefix_hits", plan.n_shared)
            reg.set("serve/prefix_blocks_shared",
                    self._alloc.n_shared_blocks)
        self._rspan(req, "prefill", pt0, dur, blocks=plan.n_new,
                    shared=plan.n_shared)
        if req.rid in self._chunking:
            # a fresh job runs its first chunk NOW (it owes this
            # round's chunk budget), and every remaining chunk too
            # when the device was idle at phase start — a solo submit
            # still stages fully, and therefore admits and decodes,
            # in its first step
            self._run_job(self._chunking[req.rid], rec,
                          all_chunks=idle)
            if req.rid in self._chunking:
                return "chunking"
            return "ready"
        self._finalize_stage(req, P_len, prompt_row)
        return "ready"

    def _build_chunk_job(self, req: Request, plan, P_len: int,
                         n_real: int, start: int,
                         prompt_row: np.ndarray) -> dict:
        """Precompute one prompt's chunk-prefill schedule: the (M,)
        flat gather index over its staged blocks, and per chunk the
        start position, padded token slice, and scatter ids for the
        ``C + block``-wide window the fixed-shape program writes back.
        Because the chunk width is a block multiple, every chunk of a
        job keeps the same sub-block offset — one compile serves every
        chunk of every (prefix, suffix) split."""
        C, blk = self._chunk_tokens, self.block
        fm = np.full((self._pq + C,), -1, np.int32)
        intra = np.arange(blk, dtype=np.int32)
        for j in range(n_real):
            w = min(blk, P_len - j * blk)
            fm[j * blk:j * blk + w] = plan.table[j] * blk + intra[:w]
        nw = C // blk + 1
        starts, toks, ids = [], [], []
        t = start
        while t < P_len:
            starts.append(t)
            tk = np.full((C,), max(self.pad_id, 0), np.int32)
            w = min(C, P_len - t)
            tk[:w] = req.prompt[t:t + w]
            toks.append(tk)
            idr = np.full((nw,), -1, np.int32)
            wb0 = t // blk
            for j in range(nw):
                wb = wb0 + j
                if wb < plan.n_shared or wb >= n_real:
                    continue            # shared or beyond the prompt
                if wb * blk >= t + C:
                    continue            # unwritten trailing window
                idr[j] = plan.table[wb]
            ids.append(idr)
            t += C
        return {"req": req, "fm": fm, "starts": starts, "toks": toks,
                "ids": ids, "next": 0, "p_len": P_len,
                "prompt_row": prompt_row}

    def _run_job(self, job: dict, rec, all_chunks: bool) -> None:
        """Dispatch the job's next chunk (or every remaining chunk)
        through the fixed-shape chunk-prefill program; finalize the
        staging when the last chunk lands.  Compiles caused by this
        request carry its trace id as the ledger exemplar."""
        req = job["req"]
        n = len(job["starts"]) - job["next"] if all_chunks else 1
        led = get_ledger()
        prev = led.exemplar
        led.exemplar = req.trace_id
        pt0 = time.perf_counter()
        try:
            for _ in range(n):
                k = job["next"]
                t = job["starts"][k]
                idr = job["ids"][k]
                with rec.span("serve/chunk_prefill", cat="serve",
                              rid=req.rid, start=int(t), chunk=k,
                              of=len(job["starts"])):
                    self._pools = self._chunk_prefill_fn(
                        self._params, self._pools,
                        self._staging_copy(job["fm"]),
                        self._staging_copy(job["toks"][k]),
                        np.int32(t), self._staging_copy(idr),
                        idr >= 0)
                job["next"] += 1
        finally:
            led.exemplar = prev
        dur = time.perf_counter() - pt0
        self.prefill_seconds += dur
        self._rspan(req, "chunk_prefill", pt0, dur, chunks=n)
        self.n_chunk_prefills += n
        get_registry().inc("serve/chunk_prefills", n)
        if job["next"] == len(job["starts"]):
            self._chunking.pop(req.rid, None)
            self._finalize_stage(req, job["p_len"],
                                 job["prompt_row"])

    def _advance_chunks(self, rec, all_chunks: bool) -> None:
        """Advance every in-flight chunk job (queue order).  A failed
        chunk quarantines ITS request only; the others keep going."""
        if not self._chunking:
            return
        for rid in [r.rid for r in self._queue
                    if r.rid in self._chunking]:
            job = self._chunking[rid]
            try:
                self._run_job(job, rec, all_chunks)
            except Exception as err:    # noqa: BLE001 — harden
                self._check_state_alive(err)
                self._shed_from_queue(
                    job["req"], "quarantined",
                    detail=f"stage: {type(err).__name__}: {err}")

    def _finalize_stage(self, req: Request, P_len: int,
                        prompt_row: np.ndarray) -> None:
        """The staged row is complete: publish it to the prefix cache
        and record the admission gather index."""
        if self.prefix_sharing:
            self._alloc.insert_cached(req.rid, req.prompt)
        flat = self._alloc.flat_gather_index(req.rid, self._pq, P_len,
                                             align="left")
        self._staged[req.rid] = (flat, prompt_row)
        self.peak_staged = max(self.peak_staged, len(self._staged))

    def _stage_traced(self, req: Request, rec, steal: bool,
                      idle: bool = True) -> str:
        """:meth:`_stage` with the request's trace id as the program
        ledger's exemplar: a compile caused by THIS request (the
        ``serve/chunk_prefill`` program's one compile, on whichever
        request reaches it first cold) links its ``compile/seconds``
        exemplar straight to the request's retained timeline — the
        same trace-id hop the latency exemplars ride."""
        led = get_ledger()
        prev = led.exemplar
        led.exemplar = req.trace_id
        try:
            return self._stage(req, rec, steal=steal, idle=idle)
        finally:
            led.exemplar = prev

    def _ensure_staged(self, req: Request, rec,
                       idle: bool = True) -> str:
        if req.rid in self._staged:
            return "ready"
        if req.rid in self._chunking:
            return "chunking"
        return self._stage_traced(req, rec, steal=True, idle=idle)

    def fork_block(self, row_id, idx: int) -> int:
        """Copy-on-write fork of a STAGED row's ``idx``-th block: if
        the block has other holders (the trie, another row) the row
        gets a fresh physical copy — device content duplicated, table
        and staged gather index repointed — and the shared original is
        never written.  Already-private blocks are left alone.
        Returns the block id the row holds afterwards.  This is the
        write-path guard primitive; the steady-state staging plan
        forks implicitly (divergent suffixes always land on fresh
        blocks), so the engine itself only needs this when a caller
        mutates staged content in place."""
        src = self._alloc.table(row_id)[idx]
        new = self._alloc.fork_for_write(row_id, idx)
        if new is None:
            return src
        self._pools = self._fork_fn(self._pools, np.int32(src),
                                    np.int32(new))
        if row_id in self._staged:
            req = next((r for r in self._queue if r.rid == row_id),
                       None)
            if req is not None:
                flat = self._alloc.flat_gather_index(
                    row_id, self._pq, req.prompt.shape[0],
                    align="left")
                self._staged[row_id] = (flat, self._staged[row_id][1])
        if row_id in self._chunking:
            # an in-flight chunk job gathers through its own flat map:
            # repoint the forked block's positions there too
            job = self._chunking[row_id]
            blk = self.block
            w = min(blk, job["p_len"] - idx * blk)
            job["fm"][idx * blk:idx * blk + w] = \
                new * blk + np.arange(w, dtype=np.int32)
            for k in range(len(job["ids"])):
                m = job["ids"][k] == src
                job["ids"][k][m] = new
        get_registry().inc("serve/prefix_forks")
        return new
