"""Fused bucketed gradient all-reduce, with hierarchical 2-stage lowering.

ChainerMN's single biggest perf lever was ``PureNcclCommunicator``'s
``batched_copy`` path: pack every gradient into one flat arena, all-reduce
the arena in a compressed dtype (``allreduce_grad_dtype``), and split the
reduction over the intra-/inter-node link hierarchy.  The JAX port's
:func:`chainermn_tpu.training.optimizers.cross_replica_mean` historically
issued one ``lax.pmean`` **per pytree leaf** — hundreds of small
collectives per step, each paying full launch latency.  This module is the
TPU-native ``batched_copy``:

- **flatten**: the grad pytree is flattened and grouped by dtype (mixed
  fp32/bf16 trees never share a buffer, so no silent up/down-casts);
- **bucket** (hybrid, the DDP-bucketing shape): leaves of at least
  ``bucket_bytes`` become *direct* buckets — one collective on the leaf
  itself, zero copies (a reshape is free); the small remainder is
  concatenated into a flat arena split at exact ``bucket_bytes``
  boundaries (the last bucket ragged, leaves freely straddling bucket
  edges).  One collective per bucket: latency amortises over the bucket
  while buckets stay small enough for XLA to overlap with neighbouring
  compute, and pack/unpack copies are only ever paid for the small
  leaves that actually need fusing;
- **compress**: with ``wire_dtype`` (bf16 recommended) buckets cross the
  wire compressed and every leaf is re-cast to its original dtype on
  unpack — the reference's fp16 allreduce, casts fused by XLA;
- **hierarchical**: given an ``inter_axis_name`` (the communicator
  reports ``inter_size > 1``), each bucket lowers as
  reduce-scatter(intra) → all-reduce(inter) → all-gather(intra) over the
  2-D mesh instead of one flat all-reduce: the DCN stage moves
  ``1/intra_size`` of the bytes, which is where multi-host bandwidth is
  won (HiCCL, arXiv:2408.05962; arXiv:2508.13397).

Collective-count guarantee: each direct leaf holds at least one full
bucket's bytes and emits exactly one collective, and the arena emits
``ceil(arena_bytes / bucket_bytes)``, so a single-dtype tree emits at
most ``ceil(total_bytes / bucket_bytes)`` collectives — the budget
:func:`chainermn_tpu.utils.comm_model.fused_collective_budget` bounds
and the tests pin on compiled HLO.
``utils/comm_model.choose_bucket_bytes`` picks ``bucket_bytes`` from the
interconnect's latency–bandwidth model.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chainermn_tpu.parallel._compat import (
    all_gather_invariant as _all_gather_invariant,
    axis_size as _axis_size,
    pcast as _pcast,
    typeof as _typeof,
)

__all__ = [
    "DEFAULT_BUCKET_BYTES",
    "PLAN_STRATEGIES",
    "FusedSpec",
    "flatten_buckets",
    "unflatten_buckets",
    "fused_allreduce",
    "fused_pmean",
    "hierarchical_allreduce",
    "reduce_scatter_allgather",
    "build_overlap_schedule",
    "overlap_exchange",
    "plan_allreduce",
]

# 4 MiB: large enough that per-collective latency is noise against wire
# time, small enough to leave XLA overlap room; choose_bucket_bytes()
# refines this from the interconnect's latency-bandwidth model.
DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024


class FusedSpec(NamedTuple):
    """Static unpack plan produced by :func:`flatten_buckets`.

    Buckets are emitted dtype-group-major, direct before arena within a
    group: for each ``(wire_dtype, direct_members, arena_members,
    n_arena_buckets)`` group entry, ``len(direct_members)`` singleton
    buckets (one whole leaf each) are followed by ``n_arena_buckets``
    arena slices whose concatenation unpacks to ``arena_members`` in
    order.  Members are ``(leaf_index, shape, orig_dtype)``;
    ``treedef`` restores the pytree; ``empties`` are zero-size leaves
    (never packed).
    """

    treedef: Any
    groups: Tuple[Tuple[Any,
                        Tuple[Tuple[int, Tuple[int, ...], Any], ...],
                        Tuple[Tuple[int, Tuple[int, ...], Any], ...],
                        int], ...]
    empties: Tuple[Tuple[int, Tuple[int, ...], Any], ...]
    n_leaves: int


def _bucket_elems(bucket_bytes: int, itemsize: int) -> int:
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes {bucket_bytes} must be positive")
    # CEIL division: a bucket of `per` elements holds >= bucket_bytes,
    # so direct leaves (size >= per) really carry a full bucket's bytes
    # and the arena splits into <= ceil(arena_bytes/bucket_bytes) slices
    # — floor would break the fused_collective_budget guarantee for
    # bucket_bytes that aren't a multiple of itemsize (choose_bucket_bytes
    # returns arbitrary sqrt-derived ints), at the price of buckets
    # overshooting bucket_bytes by at most itemsize-1 bytes.
    return -(-bucket_bytes // itemsize)


def _member(leaves, i):
    return (i, tuple(leaves[i].shape), jnp.dtype(leaves[i].dtype))


def _wire_dtype_for(dtype, wire_dtype):
    """The dtype a leaf actually crosses the wire in — the ONE copy of
    the non-float exemption rule: compression applies to FLOAT leaves
    under a FLOAT wire dtype only (an int32 or bool round-tripped
    through bf16's 8 mantissa bits is silently corrupted, and the
    reduction itself would run in the wrong arithmetic); everything
    else rides its native dtype."""
    dtype = jnp.dtype(dtype)
    if wire_dtype is not None and jnp.issubdtype(dtype, jnp.floating) \
            and jnp.issubdtype(jnp.dtype(wire_dtype), jnp.floating):
        return jnp.dtype(wire_dtype)
    return dtype


def flatten_buckets(
    grads,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
) -> Tuple[List[jax.Array], FusedSpec]:
    """Flatten a grad pytree into dtype-grouped flat buckets.

    Returns ``(buckets, spec)``: a list of 1-D arrays in the wire dtype
    — whole-leaf *direct* buckets (wire size ≥ ``bucket_bytes``; packed
    copy-free) followed, per dtype group, by arena slices of exactly
    ``bucket_bytes`` (last one ragged) covering the small leaves — plus
    the static :class:`FusedSpec` that :func:`unflatten_buckets` needs
    to invert the packing.  Zero-size leaves ride the spec only.
    """
    leaves, treedef = jax.tree.flatten(grads)
    by_dtype: dict = {}
    empties = []
    for i, leaf in enumerate(leaves):
        if leaf.size == 0:
            empties.append(_member(leaves, i))
            continue
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)

    buckets: List[jax.Array] = []
    groups = []
    for dtype, idxs in by_dtype.items():
        wire = _wire_dtype_for(dtype, wire_dtype)
        per = _bucket_elems(bucket_bytes, wire.itemsize)

        def _wire(v):
            return v if v.dtype == wire else v.astype(wire)

        direct = [i for i in idxs if leaves[i].size >= per]
        small = [i for i in idxs if leaves[i].size < per]
        for i in direct:
            buckets.append(_wire(leaves[i].reshape(-1)))
        n_arena = 0
        if small:
            flat = [_wire(leaves[i].reshape(-1)) for i in small]
            vec = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
            n_arena = -(-vec.size // per)
            for b in range(n_arena):
                buckets.append(vec[b * per: (b + 1) * per])
        groups.append((
            wire,
            tuple(_member(leaves, i) for i in direct),
            tuple(_member(leaves, i) for i in small),
            n_arena,
        ))
    return buckets, FusedSpec(treedef, tuple(groups), tuple(empties),
                              len(leaves))


def unflatten_buckets(buckets: Sequence[jax.Array], spec: FusedSpec):
    """Invert :func:`flatten_buckets`: re-split buckets into leaves,
    re-cast each to its original dtype, and rebuild the pytree."""
    out: List[Optional[jax.Array]] = [None] * spec.n_leaves
    pos = 0

    def _restore(flat, i, shape, dtype):
        leaf = flat.reshape(shape)
        out[i] = leaf.astype(dtype) if leaf.dtype != dtype else leaf

    for wire, direct, arena, n_arena in spec.groups:
        for i, shape, dtype in direct:
            _restore(buckets[pos], i, shape, dtype)
            pos += 1
        if n_arena:
            chunk = buckets[pos] if n_arena == 1 else jnp.concatenate(
                list(buckets[pos: pos + n_arena]))
            pos += n_arena
            off = 0
            for i, shape, dtype in arena:
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                _restore(chunk[off: off + size], i, shape, dtype)
                off += size
    for i, shape, dtype in spec.empties:
        # zero-size leaves were never packed; restore empties in place
        out[i] = jnp.zeros(shape, dtype)
    return spec.treedef.unflatten(out)


def hierarchical_allreduce(
    x: jax.Array,
    intra_axis_name: str,
    inter_axis_name: str,
    op: str = "mean",
) -> jax.Array:
    """Two-stage all-reduce of one flat bucket over a 2-D mesh:
    reduce-scatter(intra) → all-reduce(inter) → all-gather(intra).

    Wire math (ring formulas, ``s`` bucket bytes, ``k`` intra size,
    ``m`` inter size): the flat all-reduce moves ``2s(km-1)/km`` per
    device with every byte on the *slowest* link; the 2-stage form keeps
    the two ``s(k-1)/k`` halves on the fast intra links and crosses the
    slow inter links with only ``2(s/k)(m-1)/m`` — the inter (DCN)
    traffic shrinks by the intra degree.  The mean's divide runs on the
    1/k-sized shard, before the gather.
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported hierarchical op {op!r}")
    if x.ndim != 1:
        raise ValueError(f"hierarchical_allreduce wants a flat bucket, "
                         f"got shape {x.shape}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        # non-float buckets (int/bool — the packer's wire exemption):
        # psum_scatter rejects bool outright, and the shard-side
        # true-divide would round ints through float32.  Route them
        # through the same pmean/psum the per-leaf and fused-flat
        # paths use, so every strategy agrees exactly on non-float data.
        red = lax.pmean if op == "mean" else lax.psum
        return red(x, (intra_axis_name, inter_axis_name))
    k = _axis_size(intra_axis_name)
    size = x.shape[0]
    pad = -size % k
    if pad:
        x = jnp.pad(x, (0, pad))
    shard = lax.psum_scatter(x, intra_axis_name, tiled=True)
    shard = lax.psum(shard, inter_axis_name)
    if op == "mean":
        world = k * _axis_size(inter_axis_name)
        shard = shard / jnp.asarray(world, shard.dtype)
    full = _all_gather_invariant(shard, intra_axis_name, tiled=True)
    return full[:size] if pad else full


def fused_allreduce(
    grads,
    axis_name: str,
    op: str = "mean",
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
    inter_axis_name: Optional[str] = None,
):
    """All-reduce a grad pytree in fused flat buckets — one collective
    per ``bucket_bytes`` of wire traffic instead of one per leaf.

    Args:
      grads: pytree of per-device gradient arrays (inside ``shard_map``).
      axis_name: mesh axis to reduce over — the *intra* axis when
        ``inter_axis_name`` is given.
      op: ``"mean"`` (gradient averaging) or ``"sum"``.
      bucket_bytes: max wire bytes per arena bucket, and the threshold
        above which a leaf rides its own copy-free direct bucket
        (:func:`chainermn_tpu.utils.comm_model.choose_bucket_bytes`
        picks a principled value).
      wire_dtype: compressed wire dtype (e.g. ``jnp.bfloat16``); leaves
        re-cast to their original dtype on unpack.
      inter_axis_name: second mesh axis for the hierarchical 2-stage
        lowering (reduce-scatter intra → all-reduce inter → all-gather
        intra).  ``None`` = flat single-axis all-reduce.

    Emits at most
    :func:`chainermn_tpu.utils.comm_model.fused_collective_budget`
    ``(total_bytes, bucket_bytes, n_dtype_groups)`` collectives — the
    per-leaf baseline emits one per leaf.
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported fused allreduce op {op!r}")
    buckets, spec = flatten_buckets(grads, bucket_bytes, wire_dtype)
    if not buckets:
        return grads

    if inter_axis_name is not None:
        reduced = [hierarchical_allreduce(b, axis_name, inter_axis_name,
                                          op=op)
                   for b in buckets]
    else:
        red = lax.pmean if op == "mean" else lax.psum
        reduced = [red(b, axis_name) for b in buckets]
    return unflatten_buckets(reduced, spec)


def fused_pmean(grads, axis_name: str, **kwargs):
    """:func:`fused_allreduce` with ``op="mean"`` — the gradient
    hot-path spelling."""
    return fused_allreduce(grads, axis_name, op="mean", **kwargs)


# --------------------------------------------------------------------- #
# plan-driven execution (utils/autotune.py picks the strategy)
# --------------------------------------------------------------------- #

# The exchange-strategy space the measured autotuner searches.  Each
# names ONE lowering of "mean a grad pytree over the axis":
#   per_leaf        — one pmean per leaf (the historical baseline; wins
#                     for tiny trees where packing costs more than it
#                     amortises)
#   fused_flat      — dtype-grouped flat buckets, one all-reduce each
#   hierarchical    — fused buckets, each lowered reduce-scatter(intra)
#                     → all-reduce(inter) → all-gather(intra) over a
#                     2-D mesh (needs ``inter_axis_name``)
#   reduce_scatter  — fused buckets, each lowered reduce-scatter →
#                     all-gather over the ONE axis: same ring bytes as
#                     an all-reduce but two launches per bucket, which
#                     some fabrics/backends schedule better (and the
#                     shard-side divide halves the divide work)
#   overlap         — reverse-leaf-ordered CONTIGUOUS buckets, each
#                     exchanged as soon as the backward pass produces
#                     its gradients (:func:`overlap_exchange`): wire
#                     time hides under the remaining backward compute
#                     instead of running serially after it
PLAN_STRATEGIES = ("per_leaf", "fused_flat", "hierarchical",
                   "reduce_scatter", "overlap")


def _ensure_varying(x, axis_name):
    """Retype ``x`` varying over ``axis_name`` if the vma type system
    considers it invariant: psum_scatter of N identical copies divided
    by N is still the right mean, so both typings reduce correctly."""
    try:
        vma = _typeof(x).vma
    except AttributeError:  # pragma: no cover - pre-vma jax
        return x
    if axis_name in vma:
        return x
    return _pcast(x, axis_name, to="varying")


def reduce_scatter_allgather(
    x: jax.Array,
    axis_name: str,
    op: str = "mean",
) -> jax.Array:
    """Reduce one flat bucket over a SINGLE axis as reduce-scatter →
    all-gather — the two halves of a ring all-reduce issued explicitly.

    Same per-device ring bytes as ``lax.pmean`` (``2s(n-1)/n``), but two
    collective launches per bucket and the mean's divide runs on the
    1/n shard.  Whether this beats the fused all-reduce is a backend
    scheduling question — exactly what the measured autotuner settles.
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported reduce_scatter op {op!r}")
    if x.ndim != 1:
        raise ValueError(f"reduce_scatter_allgather wants a flat bucket, "
                         f"got shape {x.shape}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        # non-float buckets: psum_scatter rejects bool, and the
        # shard-side true-divide rounds ints through float32 — use the
        # same pmean/psum as the per-leaf/fused paths (exact agreement)
        red = lax.pmean if op == "mean" else lax.psum
        return red(x, axis_name)
    n = _axis_size(axis_name)
    size = x.shape[0]
    pad = -size % n
    if pad:
        x = jnp.pad(x, (0, pad))
    shard = lax.psum_scatter(_ensure_varying(x, axis_name), axis_name,
                             tiled=True)
    if op == "mean":
        shard = shard / jnp.asarray(n, shard.dtype)
    full = _all_gather_invariant(shard, axis_name, tiled=True)
    return full[:size] if pad else full


# --------------------------------------------------------------------- #
# backward-overlapped exchange (strategy "overlap")
# --------------------------------------------------------------------- #
#
# The window-end lowerings above share one structural property that
# kills compute/comm overlap: the arena concat (and, under accum, the
# microbatch scan) JOINS every gradient leaf, so the first collective
# cannot start until the LAST leaf of the backward pass exists — the
# compiled schedule clusters all exchange collectives after the last
# backward op.  The overlap lowering removes every cross-bucket join:
# leaves are walked in REVERSE flatten order (backward produces the
# last layer's gradients first, so reversed pytree order ≈ production
# order), packed into contiguous runs of ~bucket_bytes, and each
# bucket's reduce-scatter→all-gather (or all-reduce) depends ONLY on
# that bucket's leaves.  The scheduler is then free — and, measured on
# the compiled HLO (``assert_overlap_collectives``), actually does —
# to start bucket k's collective while the backward is still producing
# bucket k+1's gradients.
#
# Bucket-boundary anchors: each bucket's wire vector is threaded
# through ``lax.optimization_barrier`` together with a 1-element token
# of the PREVIOUS bucket's reduced output.  This pins the stream order
# (bucket k's collective cannot be hoisted before bucket k-1's) and,
# critically, stops XLA's collective combiner from re-fusing the
# buckets into one window-end collective — which would silently
# reintroduce the join this lowering exists to remove.


def _normalize_schedule(schedule) -> Tuple[Tuple[int, str, str], ...]:
    """Coerce a schedule carrier (dicts from a JSON plan, tuples, or
    lists) to ``((n_leaves, mode, via), ...)`` and validate it."""
    out = []
    for entry in schedule:
        if isinstance(entry, dict):
            leaves = entry.get("leaves")
            mode = entry.get("mode", "eager")
            via = entry.get("via", "rs")
        else:
            seq = tuple(entry)
            leaves = seq[0]
            mode = seq[1] if len(seq) > 1 else "eager"
            via = seq[2] if len(seq) > 2 else "rs"
        if not isinstance(leaves, int) or leaves < 1:
            raise ValueError(
                f"schedule entry wants a positive leaf count, got "
                f"{leaves!r}")
        if mode not in ("eager", "deferred"):
            raise ValueError(
                f"schedule mode {mode!r} not one of ('eager', "
                f"'deferred')")
        if via not in ("rs", "ar"):
            raise ValueError(
                f"schedule via {via!r} not one of ('rs', 'ar')")
        out.append((leaves, mode, via))
    if not out:
        raise ValueError("empty overlap schedule")
    return tuple(out)


def build_overlap_schedule(
    grads,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
) -> Tuple[dict, ...]:
    """Derive the default (all-eager) overlap schedule for a grad
    pytree: the REVERSED non-empty-leaf sequence is cut into contiguous
    buckets of at least ``bucket_bytes`` wire bytes (floats count at
    the compressed ``wire_dtype`` itemsize; the last bucket is ragged).

    Returns a tuple of ``{"leaves": k, "mode": "eager", "via": "rs"}``
    dicts — the JSON-stable form a
    :class:`~chainermn_tpu.utils.autotune.Plan` persists — whose leaf
    counts sum to the tree's non-empty leaf count.  Leaf *sizes* (not
    structure) drive the boundaries, so the same helper serves
    ``jax.ShapeDtypeStruct`` trees (the autotuner's candidate builder).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes {bucket_bytes} must be positive")

    def _size(leaf) -> int:
        return int(np.prod(leaf.shape, dtype=np.int64)) if leaf.shape \
            else 1

    leaves = [l for l in jax.tree.leaves(grads) if _size(l)]
    schedule = []
    run, run_bytes = 0, 0
    for leaf in reversed(leaves):
        run += 1
        run_bytes += _size(leaf) * \
            _wire_dtype_for(leaf.dtype, wire_dtype).itemsize
        if run_bytes >= bucket_bytes:
            schedule.append({"leaves": run, "mode": "eager", "via": "rs"})
            run, run_bytes = 0, 0
    if run:
        schedule.append({"leaves": run, "mode": "eager", "via": "rs"})
    if not schedule:
        # every leaf empty: a 1-bucket schedule keeps callers branch-free
        schedule.append({"leaves": 1, "mode": "eager", "via": "rs"})
    return tuple(schedule)


def overlap_exchange(
    grads,
    axis_name: str,
    op: str = "mean",
    schedule=None,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
    inter_axis_name: Optional[str] = None,
):
    """Exchange a grad pytree in reverse-leaf-ordered contiguous
    buckets, each emitted as its gradients become available — the
    backward-overlapped lowering (strategy ``"overlap"``).

    Args:
      grads: pytree of per-device gradients (inside ``shard_map``).
        The exchange collectives carry per-bucket dependencies only, so
        a bucket's collective can start while the backward pass is
        still producing the NEXT bucket's gradients — provided the
        caller's program keeps those gradients join-free (the
        ``StandardUpdater`` peels the window-final microbatch out of
        its accumulation scan for exactly this reason).
      axis_name: mesh axis to reduce over.
      op: ``"mean"`` or ``"sum"``.
      schedule: bucket plan over the REVERSED non-empty-leaf sequence —
        ``({"leaves": k, "mode": "eager"|"deferred",
        "via": "rs"|"ar"}, ...)`` (dicts or tuples).  ``eager`` buckets
        stream in reverse-layer order under the backward; ``deferred``
        buckets are held and exchanged after the eager stream (the
        window-end regime, per bucket).  ``via`` picks
        reduce-scatter→all-gather (``rs``, the default — the ZeRO-
        friendly two-launch form) or a single all-reduce (``ar``).
        ``None`` derives the all-eager default from ``bucket_bytes``
        (:func:`build_overlap_schedule`).
      bucket_bytes / wire_dtype: as :func:`fused_allreduce`; the
        non-float wire exemption applies identically (ints and bools
        never cross the wire compressed).
      inter_axis_name: when given, each bucket lowers hierarchically
        over the 2-D mesh (:func:`hierarchical_allreduce`) instead of
        ``via`` — the stream/anchor structure is unchanged.

    Dtype runs: a bucket may span leaves of several dtypes; each
    maximal same-wire-dtype run inside the bucket is packed (and, for
    multi-leaf runs, concatenated) into one flat vector per collective.
    Only ADJACENT leaves ever share a concat, so no bucket waits on
    gradients produced far from its own — the arena packer's global
    concat is exactly the join this lowering exists to avoid.
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported overlap exchange op {op!r}")
    leaves, treedef = jax.tree.flatten(grads)
    order = [i for i in range(len(leaves) - 1, -1, -1)
             if leaves[i].size != 0]
    if not order:
        return grads
    if schedule is None:
        schedule = build_overlap_schedule(grads, bucket_bytes, wire_dtype)
    sched = _normalize_schedule(schedule)
    n_sched = sum(k for k, _, _ in sched)
    if n_sched != len(order):
        raise ValueError(
            f"overlap schedule covers {n_sched} leaves, grad tree has "
            f"{len(order)} non-empty leaves — the plan was tuned for a "
            f"different payload signature")

    def _wire_of(dtype):
        return _wire_dtype_for(dtype, wire_dtype)

    # cut the reversed leaf order into (bucket, mode, via) groups
    buckets = []
    pos = 0
    for k, mode, via in sched:
        buckets.append((order[pos: pos + k], mode, via))
        pos += k

    out: List[Optional[jax.Array]] = list(leaves)
    red = lax.pmean if op == "mean" else lax.psum
    tok = None

    def _exchange_bucket(idxs, via):
        nonlocal tok
        # maximal same-wire-dtype runs of ADJACENT leaves
        runs = []
        for i in idxs:
            w = _wire_of(leaves[i].dtype)
            if runs and runs[-1][0] == w:
                runs[-1][1].append(i)
            else:
                runs.append((w, [i]))
        for w, run in runs:
            flat = [leaves[i].reshape(-1) for i in run]
            flat = [v if v.dtype == w else v.astype(w) for v in flat]
            vec = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
            if tok is not None:
                # bucket-boundary anchor: pin the stream order and keep
                # the collective combiner from re-joining the buckets
                vec, tok = lax.optimization_barrier((vec, tok))
            if inter_axis_name is not None:
                r = hierarchical_allreduce(vec, axis_name,
                                           inter_axis_name, op=op)
            elif via == "rs":
                r = reduce_scatter_allgather(vec, axis_name, op=op)
            else:
                r = red(vec, axis_name)
            tok = r[:1]
            off = 0
            for i in run:
                size = leaves[i].size
                piece = r[off: off + size].reshape(leaves[i].shape)
                out[i] = piece if piece.dtype == leaves[i].dtype \
                    else piece.astype(leaves[i].dtype)
                off += size

    for idxs, mode, via in buckets:
        if mode == "eager":
            _exchange_bucket(idxs, via)
    for idxs, mode, via in buckets:
        if mode == "deferred":
            _exchange_bucket(idxs, via)
    return treedef.unflatten(out)


def _plan_fields(plan) -> Tuple[str, int, Optional[str]]:
    """Normalise a plan carrier (``utils.autotune.Plan``, a plain dict,
    or anything with the three attributes) to
    ``(strategy, bucket_bytes, wire_dtype_name)``."""
    if isinstance(plan, dict):
        strategy = plan.get("strategy")
        bucket = plan.get("bucket_bytes")
        wire = plan.get("wire_dtype")
    else:
        strategy = getattr(plan, "strategy", None)
        bucket = getattr(plan, "bucket_bytes", None)
        wire = getattr(plan, "wire_dtype", None)
    if strategy not in PLAN_STRATEGIES:
        raise ValueError(
            f"plan strategy {strategy!r} not one of {PLAN_STRATEGIES}")
    return strategy, int(bucket or DEFAULT_BUCKET_BYTES), wire


def _plan_schedule(plan):
    """The plan's overlap ``schedule`` (or None for the derived
    default) — tolerated on any carrier shape ``_plan_fields`` takes."""
    if isinstance(plan, dict):
        return plan.get("schedule")
    return getattr(plan, "schedule", None)


def plan_allreduce(
    grads,
    axis_name: str,
    plan,
    op: str = "mean",
    inter_axis_name: Optional[str] = None,
):
    """Exchange a grad pytree according to a tuned plan — the execution
    half of :mod:`chainermn_tpu.utils.autotune`.

    ``plan`` carries ``(strategy, bucket_bytes, wire_dtype)`` — a
    :class:`~chainermn_tpu.utils.autotune.Plan`, its ``to_dict()`` form,
    or any object with those attributes.  ``strategy`` is one of
    :data:`PLAN_STRATEGIES`; ``hierarchical`` requires
    ``inter_axis_name`` to be bound by the enclosing ``shard_map``
    (plans are keyed by mesh signature, so a hierarchical plan only ever
    reaches a mesh that has the second axis).
    """
    strategy, bucket_bytes, wire_name = _plan_fields(plan)
    wire = jnp.dtype(wire_name) if wire_name else None

    if strategy == "per_leaf":
        red = lax.pmean if op == "mean" else lax.psum

        def one(g):
            if g.size == 0:
                return g
            # same non-float exemption as the fused packer: ints/bools
            # never cross the wire compressed
            if wire is not None and jnp.issubdtype(g.dtype, jnp.floating):
                return red(g.astype(wire), axis_name).astype(g.dtype)
            return red(g, axis_name).astype(g.dtype)

        return jax.tree.map(one, grads)

    if strategy == "fused_flat":
        return fused_allreduce(grads, axis_name, op=op,
                               bucket_bytes=bucket_bytes, wire_dtype=wire)
    if strategy == "hierarchical":
        if inter_axis_name is None:
            raise ValueError(
                "plan strategy 'hierarchical' needs inter_axis_name (a "
                "second mesh axis bound by the enclosing shard_map); "
                "this plan was tuned for a 2-D mesh signature")
        return fused_allreduce(grads, axis_name, op=op,
                               bucket_bytes=bucket_bytes, wire_dtype=wire,
                               inter_axis_name=inter_axis_name)
    if strategy == "overlap":
        return overlap_exchange(grads, axis_name, op=op,
                                schedule=_plan_schedule(plan),
                                bucket_bytes=bucket_bytes,
                                wire_dtype=wire,
                                inter_axis_name=inter_axis_name)

    # reduce_scatter: fused buckets, each lowered rs -> ag over the axis
    buckets, spec = flatten_buckets(grads, bucket_bytes, wire)
    if not buckets:
        return grads
    reduced = [reduce_scatter_allgather(b, axis_name, op=op)
               for b in buckets]
    return unflatten_buckets(reduced, spec)
