"""Differentiable collective ops, used *inside* jitted/shard_mapped code.

TPU-native replacement for ChainerMN's collective ``FunctionNode``s
(reference: ``chainermn/functions/collective_communication.py`` —
``AllGather``, ``AllToAll``, ``Bcast``, ``Gather``, ``Scatter``; unverified,
mount empty, see SURVEY.md).

The reference had to hand-write backward passes that fired reversed MPI
collectives (allgather's backward is an alltoall-reduce of grads, etc.).
In JAX the ``lax`` collectives already carry their transpose rules —
``psum`` ⇄ identity-broadcast, ``all_gather`` ⇄ ``psum_scatter``,
``ppermute`` ⇄ inverse permutation — so these wrappers exist to (a) give
reference users the names and calling conventions they know, (b) pin down
root-collective semantics (bcast/scatter/gather) which have no direct lax
op, with VJPs that match the reference's mathematical behaviour.

All functions take ``axis_name`` — the mesh axis of the enclosing
``shard_map``/``pjit`` — instead of a communicator object: inside traced
code the mesh axis *is* the communicator.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "allreduce", "pmean", "psum",
    "allgather", "alltoall", "bcast", "gather", "scatter",
    "reduce_scatter",
]


def psum(x, axis_name: str):
    """Sum across the mesh axis (differentiable; transpose = broadcast)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    """Mean across the mesh axis — the gradient-allreduce hot path."""
    return lax.pmean(x, axis_name)


def allreduce(x, axis_name: str, op: str = "sum"):
    """ChainerMN-parity allreduce. ``op`` in {sum, mean, max, min}."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unsupported allreduce op {op!r}")


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = False):
    """Gather every rank's ``x`` along ``axis`` on all ranks.

    Backward (from lax's transpose rule) is ``psum_scatter`` — exactly the
    reduce-scatter the reference implemented by hand in
    ``AllGather.backward``.
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def alltoall(x, axis_name: str, split_axis: int = 0, concat_axis: int = 0):
    """Scatter ``split_axis`` across ranks, gather received along
    ``concat_axis``. Self-transposing: backward is the inverse alltoall."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0,
                   tiled: bool = True):
    """Sum across ranks then scatter slices — backward of allgather,
    exposed first-class (the reference buried it inside pure_nccl)."""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def bcast(x, axis_name: str, root: int = 0):
    """Every rank returns root's ``x``.

    Implemented as ``psum(mask * x)`` — one collective, and the automatic
    transpose gives the correct backward: root's gradient is the *sum* of
    all ranks' output gradients, other ranks get zero (matching the
    reference's ``Bcast.backward`` gather-sum).
    """
    idx = lax.axis_index(axis_name)
    # where-mask, not multiply: keeps NaN/inf in non-root buffers (which the
    # reference's Bcast never read) from poisoning the sum.
    return lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)), axis_name)


def gather(x, axis_name: str, root: int = 0, axis: int = 0):
    """Gather every rank's ``x`` to ``root``; non-root ranks get zeros.

    SPMD note: every rank runs the same all_gather (there is no "do
    nothing elsewhere" in one program), but the documented contract —
    only root receives the data — is honoured by masking the result to
    zeros off-root, so code that (wrongly) reads a non-root result gets
    a loud all-zeros instead of silently using an allgather.  Want the
    value everywhere?  That is :func:`allgather`.  The masking also
    makes the backward exact ``Gather.backward`` semantics: grads flow
    from *root's* output only (scatter of root's grads), other ranks'
    output cotangents are discarded by the mask's transpose.
    """
    full = lax.all_gather(x, axis_name, axis=axis, tiled=False)
    idx = lax.axis_index(axis_name)
    return jnp.where(idx == root, full, jnp.zeros_like(full))


def scatter(x, axis_name: str, root: int = 0, axis: int = 0):
    """Rank ``i`` returns slice ``i`` (along ``axis``) of root's ``x``.

    ``x`` must carry a world-sized dimension at ``axis`` on every rank
    (only root's is read — the mirror of :func:`gather`'s root-only
    output, e.g. ``scatter(gather(x, ax, root=r), ax, root=r) == x``).
    Backward: root receives the gather of output grads — the
    reference's ``Scatter.backward``.
    """
    full = bcast(x, axis_name, root=root)
    idx = lax.axis_index(axis_name)
    return lax.dynamic_index_in_dim(full, idx, axis=axis, keepdims=False)
