"""Collective-plan IR — one searched, cached exchange plan for every
communication pattern.

Until now only the optimizer gradient exchange had measured plans
(``utils.autotune``); FSDP all-gathers, MoE all-to-all, ring-attention
ppermutes and pipeline send/recv were hard-coded lowerings that could
not be tuned per topology.  This module is the HiCCL/GC3 style split of
*what* a pattern exchanges from *how* the wire moves it:

- a **payload descriptor** (:class:`LeafDesc`) records, per leaf, the
  dtype / local shape / layout (the dim a gather reassembles along);
- a **program** (:class:`PlanProgram`) is a list of primitive
  :class:`PlanStep`\\ s — ``reduce_scatter``, ``all_gather``,
  ``all_reduce``, ``all_to_all``, ``ppermute``, ``send_recv``,
  ``fuse``, ``cast_wire``, ``barrier`` — over SYMBOLIC mesh-axis roles
  (``"main"``, ``"inter"``) bound to concrete axis names at lowering;
- the **interpreter** (:class:`_Lowering`) lowers a program to
  ``jax.lax`` collectives inside the caller's ``shard_map``.

Programs are plain data (JSON-stable dicts), so they ride the existing
plan cache / rank-0-broadcast / drift-guard machinery unchanged:
``utils.autotune.autotune_pattern_plan`` enumerates the candidate
programs below, probes them on the live mesh, and persists the winner
under a ``plan_key(variant="plan-ir/<pattern>/...")`` entry.

Correctness invariants the interpreter maintains:

- every *native* (no ``cast_wire``) program is pure data movement —
  candidates of one pattern are BITWISE equal to the legacy lowering;
- ``cast_wire`` applies the ONE non-float exemption rule
  (:func:`chainermn_tpu.ops.fused._wire_dtype_for`): int/bool leaves
  ride their native dtype, and both casts are pinned against the
  collective with ``lax.optimization_barrier`` so XLA cannot widen the
  wire back (the fsdp_gather hazard);
- ``fuse`` groups lanes by dtype (stacking equal shapes, else
  ravel-concat) and the interpreter un-fuses — and restores original
  dtypes — after the last step, so callers always get back the exact
  tree structure they passed in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .fused import _wire_dtype_for

__all__ = [
    "PRIMITIVES",
    "PATTERNS",
    "LeafDesc",
    "PlanStep",
    "PlanProgram",
    "step",
    "describe_payload",
    "describe_state_payload",
    "ensure_program",
    "lower_fsdp_gather",
    "lower_moe_all_to_all",
    "lower_ring_permute",
    "lower_pipeline_edge",
    "enumerate_fsdp_gather_programs",
    "enumerate_moe_a2a_programs",
    "enumerate_ring_permute_programs",
    "enumerate_pipeline_edge_programs",
    "enumerate_pattern_programs",
]

def _pin(x):
    """``lax.optimization_barrier`` where the running jax supports it
    inside ``shard_map``.  Pre-vma shard_map (jax 0.4.x ``check_rep``)
    has no replication rule for the primitive and crashes on it, so
    there the pin degrades to identity — XLA may then widen a wire
    cast back to the source dtype, which costs bytes (on hardware
    that matters; probes measure it) but never correctness."""
    from chainermn_tpu.parallel._compat import HAS_VMA

    return lax.optimization_barrier(x) if HAS_VMA else x


# the primitive step vocabulary — a program is a sequence of these
PRIMITIVES = ("reduce_scatter", "all_gather", "all_reduce", "all_to_all",
              "ppermute", "send_recv", "fuse", "cast_wire", "barrier")

# the ported call-site patterns (each names a candidate enumerator
# below and a `comm/plan_<pattern>` span at its lowering entry point)
PATTERNS = ("fsdp_gather", "moe_all_to_all", "ring_permute",
            "pipeline_edge")

# primitives that put bytes on the wire (everything else is on-device
# data movement) — comm_model.primitive_cost mirrors this split
WIRE_PRIMITIVES = ("reduce_scatter", "all_gather", "all_reduce",
                   "all_to_all", "ppermute", "send_recv")


# --------------------------------------------------------------------- #
# payload descriptors
# --------------------------------------------------------------------- #


@dataclass(frozen=True)
class LeafDesc:
    """Per-leaf payload signature: local shape, dtype, and layout —
    the dim a gather/scatter reassembles along (``None`` for leaves
    with no distributed dim, e.g. all-to-all operands whose axes are
    relabeled rather than widened)."""

    shape: Tuple[int, ...]
    dtype: str
    layout: Optional[int] = None

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= int(s)
        return n

    @property
    def nbytes(self) -> int:
        return self.size * jnp.dtype(self.dtype).itemsize

    def to_dict(self) -> dict:
        return {"shape": list(self.shape), "dtype": self.dtype,
                "layout": self.layout}


def describe_payload(tree, layouts=None) -> Tuple[LeafDesc, ...]:
    """Flattened-order payload descriptors for ``tree``; ``layouts``
    (a matching pytree of Optional[int], e.g. ``fsdp_dims``' output)
    supplies per-leaf layout dims."""
    leaves, treedef = jax.tree.flatten(tree)
    lay: Sequence[Optional[int]]
    if layouts is None:
        lay = [None] * len(leaves)
    else:
        lay = treedef.flatten_up_to(layouts)
    return tuple(
        LeafDesc(shape=tuple(int(s) for s in jnp.shape(leaf)),
                 dtype=str(jnp.dtype(getattr(leaf, "dtype",
                                             jnp.asarray(leaf).dtype))),
                 layout=(None if d is None else int(d)))
        for leaf, d in zip(leaves, lay))


def describe_state_payload(layouts, world: Optional[int] = None
                           ) -> Tuple[LeafDesc, ...]:
    """Payload descriptors for the LOCAL (per-member) shard payload a
    sharded-state exchange moves, derived straight from per-leaf layout
    signatures (``parallel.sharded_state.LeafLayout`` objects or their
    record dicts + shape/dtype) — never from live arrays, so plans can
    be tuned before any state is materialized.

    Kind mapping: ``fsdp`` → the dim-sharded local slice with
    ``layout`` = the shard dim (what ``lower_fsdp_gather`` widens);
    ``shard`` → the flat ``(ceil(size/world),)`` ZeRO shard, gathered
    along axis 0; ``rep``/``stack`` → the full leaf, no distributed
    dim (rides the exchange unchanged).
    """
    descs = []
    for spec in layouts:
        get = (spec.get if isinstance(spec, dict)
               else lambda k, _s=spec: getattr(_s, k, None))
        kind = get("kind")
        shape = tuple(int(s) for s in (get("shape") or ()))
        dtype = str(get("dtype") or "float32")
        w = int(world if world is not None else get("world") or 1)
        if kind == "fsdp":
            d = int(get("dim"))
            if shape[d] % w:
                raise ValueError(
                    f"fsdp leaf dim {d} (length {shape[d]}) not "
                    f"divisible by world {w}")
            local = list(shape)
            local[d] //= w
            descs.append(LeafDesc(tuple(local), dtype, layout=d))
        elif kind == "shard":
            size = int(get("size"))
            descs.append(LeafDesc((-(-size // w),), dtype, layout=0))
        elif kind in ("rep", "stack"):
            descs.append(LeafDesc(shape, dtype, layout=None))
        else:
            raise ValueError(f"unknown layout kind {kind!r}")
    return tuple(descs)


# --------------------------------------------------------------------- #
# steps & programs
# --------------------------------------------------------------------- #


@dataclass(frozen=True)
class PlanStep:
    """One primitive of a plan program.  ``axis`` is a SYMBOLIC role
    (``"main"`` / ``"inter"``) bound to a concrete mesh-axis name at
    lowering; ``params`` are static op parameters (sorted key/value
    pairs — hashable, JSON-stable)."""

    op: str
    axis: Optional[str] = None
    params: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if self.op not in PRIMITIVES:
            raise ValueError(
                f"unknown plan primitive {self.op!r}; expected one of "
                f"{PRIMITIVES}")

    def get(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    def replaced(self, **updates) -> "PlanStep":
        merged = dict(self.params)
        merged.update(updates)
        return PlanStep(self.op, self.axis,
                        tuple(sorted(merged.items())))

    def to_dict(self) -> dict:
        return {"op": self.op, "axis": self.axis,
                "params": dict(self.params)}

    @classmethod
    def from_dict(cls, d: dict) -> "PlanStep":
        return cls(op=d["op"], axis=d.get("axis"),
                   params=tuple(sorted((d.get("params") or {}).items())))


def step(op: str, axis: Optional[str] = None, **params) -> PlanStep:
    """Shorthand constructor: ``step("all_gather", axis="main")``."""
    return PlanStep(op, axis, tuple(sorted(params.items())))


@dataclass
class PlanProgram:
    """A candidate exchange program for one pattern: the searched /
    cached artifact.  ``label`` names the candidate in plan-cache
    metadata and bench reports (e.g. ``"fused/hier/native"``)."""

    pattern: str
    label: str
    steps: Tuple[PlanStep, ...] = ()

    def __post_init__(self):
        if self.pattern not in PATTERNS:
            raise ValueError(
                f"unknown plan pattern {self.pattern!r}; expected one "
                f"of {PATTERNS}")
        self.steps = tuple(self.steps)

    @property
    def wire_dtype(self) -> Optional[str]:
        for st in self.steps:
            if st.op == "cast_wire":
                return st.get("dtype")
        return None

    def to_dict(self) -> dict:
        return {"pattern": self.pattern, "label": self.label,
                "steps": [st.to_dict() for st in self.steps]}

    @classmethod
    def from_dict(cls, d: dict) -> "PlanProgram":
        return cls(pattern=d["pattern"], label=d.get("label", "?"),
                   steps=tuple(PlanStep.from_dict(s)
                               for s in d.get("steps", ())))


def ensure_program(obj, pattern: Optional[str] = None) -> PlanProgram:
    """Coerce a program carrier to a :class:`PlanProgram`: accepts a
    PlanProgram, its dict form, or a tuned ``autotune.Plan`` (whose
    ``program`` field holds the dict).  ``pattern`` cross-checks the
    carrier against the call site consuming it — a cached MoE program
    fed to ``fsdp_gather`` must fail loudly, not lower garbage."""
    prog = getattr(obj, "program", None)
    if prog is not None and not isinstance(obj, PlanProgram):
        obj = prog
    if isinstance(obj, dict):
        obj = PlanProgram.from_dict(obj)
    if not isinstance(obj, PlanProgram):
        raise TypeError(
            f"cannot build a PlanProgram from {type(obj).__name__}")
    if pattern is not None and obj.pattern != pattern:
        raise ValueError(
            f"plan program is for pattern {obj.pattern!r}, but this "
            f"call site lowers {pattern!r}")
    return obj


# --------------------------------------------------------------------- #
# the interpreter
# --------------------------------------------------------------------- #


@dataclass
class _Bucket:
    """One fused lane: dtype-grouped members of the input lanes.
    ``mode`` is ``"stack"`` (equal shapes — cheap axis-0 stack) or
    ``"concat"`` (ravel + concatenate)."""

    mode: str
    members: List[int]
    shapes: List[Tuple[int, ...]] = field(default_factory=list)


class _Lowering:
    """Executes a program's steps over a list of *lanes* (arrays).

    Fused lanes always carry a leading world axis (size 1 at fuse
    time); every ``all_gather`` step widens it with ``tiled=True`` at
    axis 0, so hierarchical two-stage gathers compose by construction
    (row-major (inter, intra) device order — exactly the flat gather
    over the combined axis).  Un-fusing distributes the accumulated
    factor back onto each member's layout dim."""

    def __init__(self, lanes: Sequence, descs: Sequence[LeafDesc],
                 axes: Dict[str, Optional[str]]):
        self.lanes = [jnp.asarray(x) for x in lanes]
        self.descs = list(descs)
        self.axes = axes
        self.origs = [x.dtype for x in self.lanes]
        self.buckets: Optional[List[_Bucket]] = None
        self.gather_factor = 1

    # ---- helpers ---------------------------------------------------- #

    def _axis(self, st: PlanStep) -> str:
        role = st.axis or "main"
        name = self.axes.get(role)
        if name is None:
            raise ValueError(
                f"program step {st.op!r} names axis role {role!r} but "
                f"the call site bound no such axis (got {self.axes})")
        return name

    @staticmethod
    def _perm(size: int, shift: int, wrap: bool):
        if shift not in (1, -1):
            raise ValueError(f"send_recv shift must be ±1, got {shift}")
        if shift == 1:
            perm = [(i, i + 1) for i in range(size - 1)]
            return perm + ([(size - 1, 0)] if wrap else [])
        perm = [(i + 1, i) for i in range(size - 1)]
        return perm + ([(0, size - 1)] if wrap else [])

    @staticmethod
    def _resized(lane, dim: int, new_len: int):
        # XLA rejects collectives whose gather/scatter dim is empty, so
        # zero-size lanes never hit the wire: their post-collective
        # value is fully determined by the (empty) output shape
        shape = list(lane.shape)
        shape[dim] = new_len
        return jnp.zeros(tuple(shape), lane.dtype)

    # ---- primitives ------------------------------------------------- #

    def _cast_wire(self, st: PlanStep):
        wd = st.get("dtype")
        if wd is None:
            return
        for i, lane in enumerate(self.lanes):
            eff = _wire_dtype_for(lane.dtype, jnp.dtype(wd))
            if eff != lane.dtype:
                # barrier pins the narrow-cast against the collective:
                # without it XLA sinks the convert across the wire op
                # and the transfer silently widens to the source dtype
                self.lanes[i] = _pin(lane.astype(eff))

    def _fuse(self, st: PlanStep):
        if self.buckets is not None:
            raise ValueError("fuse applied twice in one program")
        groups: Dict[str, List[int]] = {}
        for i, lane in enumerate(self.lanes):
            groups.setdefault(str(lane.dtype), []).append(i)
        buckets: List[_Bucket] = []
        fused_lanes = []
        for _dt, idxs in groups.items():
            shapes = [tuple(self.lanes[i].shape) for i in idxs]
            if len(set(shapes)) == 1:
                vec = jnp.stack([self.lanes[i] for i in idxs])
                buckets.append(_Bucket("stack", idxs, shapes))
            else:
                vec = jnp.concatenate(
                    [self.lanes[i].reshape(-1) for i in idxs])
                buckets.append(_Bucket("concat", idxs, shapes))
            fused_lanes.append(vec[None])   # leading world axis, size 1
        self.buckets = buckets
        self.lanes = fused_lanes

    def _all_gather(self, st: PlanStep):
        name = self._axis(st)
        size = lax.axis_size(name)
        if self.buckets is not None:
            self.lanes = [
                self._resized(lane, 0, lane.shape[0] * size)
                if lane.size == 0
                else lax.all_gather(lane, name, axis=0, tiled=True)
                for lane in self.lanes]
            self.gather_factor *= size
            return
        out = []
        for lane, desc in zip(self.lanes, self.descs):
            dim = desc.layout if desc.layout is not None else 0
            if lane.size == 0:
                out.append(self._resized(lane, dim,
                                         lane.shape[dim] * size))
            else:
                out.append(lax.all_gather(lane, name, axis=dim,
                                          tiled=True))
        self.lanes = out

    def _reduce(self, st: PlanStep, scatter: bool):
        name = self._axis(st)
        op = st.get("op", "add")
        if op not in ("add", "mean"):
            raise ValueError(f"reduce op {op!r} not in (add, mean)")
        out = []
        for lane, desc in zip(self.lanes,
                              self.descs if self.buckets is None
                              else [None] * len(self.lanes)):
            if not scatter:
                red = lane if lane.size == 0 else \
                    (lax.pmean if op == "mean" else lax.psum)(lane, name)
            else:
                dim = 0
                if desc is not None and desc.layout is not None:
                    dim = desc.layout
                if lane.shape[dim] % lax.axis_size(name):
                    raise ValueError(
                        f"reduce_scatter dim {dim} (length "
                        f"{lane.shape[dim]}) not divisible by axis "
                        f"{name!r} size {lax.axis_size(name)}")
                if lane.size == 0:
                    red = self._resized(
                        lane, dim,
                        lane.shape[dim] // lax.axis_size(name))
                else:
                    red = lax.psum_scatter(lane, name,
                                           scatter_dimension=dim,
                                           tiled=True)
                    if op == "mean":
                        red = red / lax.axis_size(name)
            out.append(red)
        self.lanes = out

    def _all_to_all(self, st: PlanStep):
        if self.buckets is not None:
            raise ValueError(
                "all_to_all on fused lanes is not supported — it "
                "relabels a per-lane axis; fuse has no meaning here")
        name = self._axis(st)
        sa = int(st.get("split_axis", 0))
        ca = int(st.get("concat_axis", 0))
        chunks = int(st.get("chunks", 1))
        chunk_axis = st.get("chunk_axis")
        out = []
        for lane in self.lanes:
            if lane.size == 0:
                size = lax.axis_size(name)
                moved = self._resized(lane, sa, lane.shape[sa] // size)
                out.append(self._resized(moved, ca,
                                         moved.shape[ca] * size))
                continue
            if chunks <= 1:
                out.append(lax.all_to_all(lane, name, split_axis=sa,
                                          concat_axis=ca, tiled=True))
                continue
            d = int(chunk_axis if chunk_axis is not None
                    else lane.ndim - 1)
            if d == sa or d == ca:
                raise ValueError(
                    f"all_to_all chunk_axis {d} collides with "
                    f"split/concat axes ({sa}, {ca}) — chunked results "
                    "would interleave wrong")
            if lane.shape[d] % chunks:
                raise ValueError(
                    f"all_to_all chunk axis {d} (length "
                    f"{lane.shape[d]}) not divisible by {chunks}")
            pieces = jnp.split(lane, chunks, axis=d)
            moved = [lax.all_to_all(p, name, split_axis=sa,
                                    concat_axis=ca, tiled=True)
                     for p in pieces]
            out.append(jnp.concatenate(moved, axis=d))
        self.lanes = out

    def _permute(self, st: PlanStep):
        name = self._axis(st)
        size = lax.axis_size(name)
        perm = self._perm(size, int(st.get("shift", 1)),
                          bool(st.get("wrap", True)))
        if not perm:                       # degenerate 1-device edge
            return
        self.lanes = [lane if lane.size == 0
                      else lax.ppermute(lane, name, perm=perm)
                      for lane in self.lanes]

    def _barrier(self, _st: PlanStep):
        self.lanes = list(_pin(tuple(self.lanes)))

    # ---- finalization ----------------------------------------------- #

    def _merge_world(self, piece, layout: Optional[int]):
        """Fold the leading gathered factor into the member's layout
        dim — block order matches ``lax.all_gather(tiled=True)``."""
        f = piece.shape[0]
        if f == 1:
            return piece[0]
        if layout is None:
            raise ValueError(
                "program gathered fused lanes but a member has no "
                "layout dim to reassemble along")
        d = int(layout)
        moved = jnp.moveaxis(piece, 0, d)
        shape = list(moved.shape)
        shape[d: d + 2] = [shape[d] * shape[d + 1]]
        return moved.reshape(shape)

    def _unfuse(self):
        if self.buckets is None:
            return
        restored: List[Any] = [None] * len(self.descs)
        for lane, bucket in zip(self.lanes, self.buckets):
            if bucket.mode == "stack":
                for j, i in enumerate(bucket.members):
                    restored[i] = self._merge_world(
                        lane[:, j], self.descs[i].layout)
            else:
                off = 0
                for i, shape in zip(bucket.members, bucket.shapes):
                    size = 1
                    for s in shape:
                        size *= s
                    piece = lane[:, off: off + size]
                    piece = piece.reshape((lane.shape[0],) + shape)
                    restored[i] = self._merge_world(
                        piece, self.descs[i].layout)
                    off += size
        self.lanes = restored
        self.buckets = None

    def _restore_dtypes(self):
        out = []
        for lane, orig in zip(self.lanes, self.origs):
            if lane.dtype != orig:
                # the cast-back twin of _cast_wire's barrier: without
                # it XLA hoists the widen above the collective
                lane = _pin(lane).astype(orig)
            out.append(lane)
        self.lanes = out

    _DISPATCH = {
        "cast_wire": _cast_wire,
        "fuse": _fuse,
        "all_gather": _all_gather,
        "all_to_all": _all_to_all,
        "ppermute": _permute,
        "send_recv": _permute,
        "barrier": _barrier,
    }

    def run(self, steps: Sequence[PlanStep]) -> List:
        for st in steps:
            if st.op == "all_reduce":
                self._reduce(st, scatter=False)
            elif st.op == "reduce_scatter":
                self._reduce(st, scatter=True)
            else:
                self._DISPATCH[st.op](self, st)
        self._unfuse()
        self._restore_dtypes()
        return self.lanes


def lower_program(program, lanes, descs, axes: Dict[str, Optional[str]]):
    """Low-level entry: run ``program`` over explicit lanes/descs with
    ``axes`` binding symbolic roles to mesh-axis names.  The pattern
    entry points below are the supported surface; this exists for
    tests and custom patterns."""
    program = ensure_program(program)
    return _Lowering(lanes, descs, axes).run(program.steps)


# --------------------------------------------------------------------- #
# pattern entry points (the four ported call sites)
# --------------------------------------------------------------------- #


def _recorder():
    from chainermn_tpu.utils.telemetry import get_recorder

    return get_recorder()


def lower_fsdp_gather(program, params, dims, *,
                      axis_name: str = "data",
                      inter_axis_name: Optional[str] = None):
    """Lower an ``fsdp_gather`` plan: all-gather the sharded leaves
    (``dims`` marks each leaf's gather dim, ``None`` = untouched) back
    to full width, per the program's strategy.  Call INSIDE shard_map —
    the just-in-time per-layer gather, exactly like the legacy path;
    AD still reduce-scatters through the gather's transpose."""
    program = ensure_program(program, "fsdp_gather")
    leaves, treedef = jax.tree.flatten(params)
    dim_list = treedef.flatten_up_to(dims)
    idxs = [i for i, d in enumerate(dim_list) if d is not None]
    if not idxs:
        return params
    lanes = [leaves[i] for i in idxs]
    descs = [LeafDesc(tuple(int(s) for s in leaves[i].shape),
                      str(leaves[i].dtype), int(dim_list[i]))
             for i in idxs]
    with _recorder().span("comm/plan_fsdp_gather", cat="comm",
                          label=program.label, n_leaves=len(idxs)):
        out = _Lowering(lanes, descs,
                        {"main": axis_name,
                         "inter": inter_axis_name}).run(program.steps)
    for i, lane in zip(idxs, out):
        leaves[i] = lane
    return treedef.unflatten(leaves)


def lower_moe_all_to_all(program, x, *, axis_name: str,
                         split_axis: int, concat_axis: int):
    """Lower one MoE dispatch/combine all-to-all.  The direction's
    split/concat axes come from the call site (dispatch: 0→1,
    combine: 1→0) and override the program's placeholders; chunking
    (``chunks``/``chunk_axis``) stays the program's choice."""
    program = ensure_program(program, "moe_all_to_all")
    steps = tuple(
        st.replaced(split_axis=int(split_axis),
                    concat_axis=int(concat_axis))
        if st.op == "all_to_all" else st for st in program.steps)
    desc = LeafDesc(tuple(int(s) for s in x.shape), str(x.dtype), None)
    with _recorder().span("comm/plan_moe_all_to_all", cat="comm",
                          label=program.label, split=int(split_axis)):
        out = _Lowering([x], [desc],
                        {"main": axis_name, "inter": None}).run(steps)
    return out[0]


def lower_ring_permute(program, operands, *, axis_name: str):
    """Lower one ring-attention rotation step: shift every operand
    (the K/V blocks) one position around the ring, fused into a single
    wire transfer or as separate ppermutes per the program."""
    program = ensure_program(program, "ring_permute")
    lanes = list(operands)
    descs = [LeafDesc(tuple(int(s) for s in x.shape), str(x.dtype),
                      None) for x in lanes]
    with _recorder().span("comm/plan_ring_permute", cat="comm",
                          label=program.label, n_operands=len(lanes)):
        out = _Lowering(lanes, descs,
                        {"main": axis_name,
                         "inter": None}).run(program.steps)
    return tuple(out)


def lower_pipeline_edge(program, x, *, axis_name: str, shift: int = 1,
                        wrap: bool = False):
    """Lower one pipeline stage hand-off (``send_recv`` neighbour
    copy).  Direction and wrap-around come from the call site (GPipe
    up edge: ``shift=1, wrap=False``; 1F1B down edge: ``shift=-1``;
    interleaved edges wrap) and override the program's placeholders."""
    program = ensure_program(program, "pipeline_edge")
    steps = tuple(
        st.replaced(shift=int(shift), wrap=bool(wrap))
        if st.op in ("send_recv", "ppermute") else st
        for st in program.steps)
    desc = LeafDesc(tuple(int(s) for s in x.shape), str(x.dtype), None)
    with _recorder().span("comm/plan_pipeline_edge", cat="comm",
                          label=program.label, shift=int(shift)):
        out = _Lowering([x], [desc],
                        {"main": axis_name, "inter": None}).run(steps)
    return out[0]


# --------------------------------------------------------------------- #
# candidate enumerators (the per-pattern search spaces)
# --------------------------------------------------------------------- #

# Enumerator contract: the FIRST program is the legacy-equivalent
# native baseline — the autotuner's parity anchor (bitwise reference
# for every native candidate, tolerance reference for wire ones).


def _wire_variants(wire_dtypes) -> List[Tuple[str, List[PlanStep]]]:
    out: List[Tuple[str, List[PlanStep]]] = []
    for wd in wire_dtypes:
        if wd is None:
            out.append(("native", []))
        else:
            wd = str(jnp.dtype(wd))
            out.append((wd, [step("cast_wire", dtype=wd)]))
    return out


def enumerate_fsdp_gather_programs(
        *, allow_hierarchical: bool = False,
        wire_dtypes: Sequence = (None,)) -> List[PlanProgram]:
    """FSDP gather candidates: {per-leaf, fused} × {flat, hierarchical
    two-stage} × wire dtypes.  Hierarchical gathers intra (``main``)
    then inter — row-major (inter, intra) block order, identical to
    the flat gather over the combined axis tuple."""
    progs = []
    tiers = [("flat", [step("all_gather", axis="main")])]
    if allow_hierarchical:
        tiers.append(("hier", [step("all_gather", axis="main"),
                               step("all_gather", axis="inter")]))
    for wire_label, pre in _wire_variants(wire_dtypes):
        for tier_label, gathers in tiers:
            for fused in (False, True):
                steps_ = list(pre)
                if fused:
                    steps_.append(step("fuse"))
                steps_ += gathers
                kind = "fused" if fused else "per_leaf"
                label = f"{kind}/{tier_label}/{wire_label}"
                progs.append(PlanProgram("fsdp_gather", label,
                                         tuple(steps_)))
    # baseline first: per_leaf/flat/native must lead regardless of
    # the wire_dtypes ordering the caller passed
    progs.sort(key=lambda p: p.label != "per_leaf/flat/native")
    return progs


def enumerate_moe_a2a_programs(
        shape: Sequence[int], *, split_axis: int = 0,
        concat_axis: int = 1, max_chunks: int = 8,
        wire_dtypes: Sequence = (None,)) -> List[PlanProgram]:
    """MoE all-to-all candidates: the single-shot transfer vs
    axis-split chunked variants (k transfers over a dim not involved
    in the relabel — bitwise-identical, trades launches for pipelining
    room) × wire dtypes."""
    shape = tuple(int(s) for s in shape)
    chunk_axis = None
    for d in range(len(shape) - 1, -1, -1):
        if d != split_axis and d != concat_axis and shape[d] > 1:
            chunk_axis = d
            break
    progs = []
    for wire_label, pre in _wire_variants(wire_dtypes):
        progs.append(PlanProgram(
            "moe_all_to_all", f"single/{wire_label}",
            tuple(pre + [step("all_to_all", axis="main",
                              split_axis=split_axis,
                              concat_axis=concat_axis)])))
        if chunk_axis is None:
            continue
        k = 2
        while k <= max_chunks and shape[chunk_axis] % k == 0 \
                and shape[chunk_axis] // k >= 1:
            progs.append(PlanProgram(
                "moe_all_to_all", f"split{k}/{wire_label}",
                tuple(pre + [step("all_to_all", axis="main",
                                  split_axis=split_axis,
                                  concat_axis=concat_axis,
                                  chunks=k, chunk_axis=chunk_axis)])))
            k *= 2
    progs.sort(key=lambda p: p.label != "single/native")
    return progs


def enumerate_ring_permute_programs(
        *, wire_dtypes: Sequence = (None,)) -> List[PlanProgram]:
    """Ring-rotation candidates: one ppermute per operand (legacy —
    K and V each launch a collective) vs fused (stack K/V, one wire
    transfer, unstack) × wire dtypes."""
    progs = []
    for wire_label, pre in _wire_variants(wire_dtypes):
        progs.append(PlanProgram(
            "ring_permute", f"separate/{wire_label}",
            tuple(pre + [step("ppermute", axis="main",
                              shift=1, wrap=True)])))
        progs.append(PlanProgram(
            "ring_permute", f"fused/{wire_label}",
            tuple(pre + [step("fuse"),
                         step("ppermute", axis="main",
                              shift=1, wrap=True)])))
    progs.sort(key=lambda p: p.label != "separate/native")
    return progs


def enumerate_pipeline_edge_programs(
        *, wire_dtypes: Sequence = (None,)) -> List[PlanProgram]:
    """Pipeline stage-edge candidates: the native neighbour copy vs
    wire-compressed variants (activation bytes halved over the hop —
    the allreduce_grad_dtype trade applied to the pipe edge)."""
    progs = []
    for wire_label, pre in _wire_variants(wire_dtypes):
        progs.append(PlanProgram(
            "pipeline_edge", f"direct/{wire_label}",
            tuple(pre + [step("send_recv", axis="main",
                              shift=1, wrap=False)])))
    progs.sort(key=lambda p: p.label != "direct/native")
    return progs


def enumerate_pattern_programs(pattern: str, **kwargs) -> List[PlanProgram]:
    """Dispatch to the pattern's enumerator — the autotuner's single
    entry point (``kwargs`` are the enumerator's own)."""
    table = {
        "fsdp_gather": enumerate_fsdp_gather_programs,
        "moe_all_to_all": enumerate_moe_a2a_programs,
        "ring_permute": enumerate_ring_permute_programs,
        "pipeline_edge": enumerate_pipeline_edge_programs,
    }
    if pattern not in table:
        raise ValueError(
            f"unknown pattern {pattern!r}; expected one of {PATTERNS}")
    return table[pattern](**kwargs)
