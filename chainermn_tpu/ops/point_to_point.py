"""Differentiable point-to-point communication for model/pipeline parallelism.

TPU-native replacement for ChainerMN's ``Send``/``Recv`` FunctionNodes and
``pseudo_connect`` (reference: ``chainermn/functions/point_to_point_communication.py``,
unverified — mount empty, see SURVEY.md).

Design shift (the SURVEY §7 "hard part (b)"): the reference used *blocking
MPI p2p between different programs* on each rank, with hand-written backward
passes that fired communication in the reverse direction, and
``pseudo_connect`` to keep the autograd graph alive across the wire so
``backward()`` wouldn't deadlock.  On TPU, p2p between pipeline stages is
``lax.ppermute`` inside one SPMD program: deadlock-freedom comes from
program identicality, and the transpose rule of ``ppermute`` (the inverse
permutation) *is* the reversed-direction backward — no hand-written
backward, no graph surgery.

``send``/``recv`` are provided as parity names over ``ppermute`` shifts;
``pseudo_connect`` survives as a graph-tie that stops XLA dead-code-
eliminating an otherwise-unused permute (the moral descendant of the
reference's dummy-variable trick).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ppermute", "send", "recv", "send_recv",
    "shift_up", "shift_down", "pseudo_connect",
]


def ppermute(x, axis_name: str, perm: Sequence[Tuple[int, int]]):
    """Raw collective-permute: ``perm`` is [(source, dest), ...]; ranks with
    no source receive zeros. Differentiable (backward = inverse perm)."""
    return jax.tree.map(
        lambda a: lax.ppermute(a, axis_name, perm=list(perm)), x)


def send(x, axis_name: str, dest: int, source: int):
    """Move ``x`` from rank ``source`` to ``dest`` (zeros elsewhere).

    Unlike the reference's per-rank call sites (rank A calls ``send``,
    rank B calls ``recv``, both block), SPMD code states the *whole*
    transfer once; every rank traces the same program.  Backward moves the
    cotangent ``dest → source`` automatically.
    """
    return ppermute(x, axis_name, [(source, dest)])


# recv is the same op viewed from the receiving side; parity alias.
recv = send


def send_recv(x, axis_name: str, perm: Sequence[Tuple[int, int]]):
    """Simultaneous multi-pair exchange (the general ChainerMN use)."""
    return ppermute(x, axis_name, perm)


def _shift_perm(n: int, delta: int, wrap: bool) -> List[Tuple[int, int]]:
    if wrap:
        return [(i, (i + delta) % n) for i in range(n)]
    return [(i, i + delta) for i in range(n) if 0 <= i + delta < n]


def shift_up(x, axis_name: str, axis_size: Optional[int] = None,
             wrap: bool = False):
    """Stage ``i`` → stage ``i+1`` (activation flow in a pipeline).
    Stage 0 receives zeros unless ``wrap`` (ring)."""
    n = axis_size or lax.axis_size(axis_name)
    return ppermute(x, axis_name, _shift_perm(n, +1, wrap))


def shift_down(x, axis_name: str, axis_size: Optional[int] = None,
               wrap: bool = False):
    """Stage ``i`` → stage ``i-1`` (gradient flow / ring reverse)."""
    n = axis_size or lax.axis_size(axis_name)
    return ppermute(x, axis_name, _shift_perm(n, -1, wrap))


def pseudo_connect(delegate, *actuals):
    """Tie ``delegate`` (e.g. a ``send`` result the local rank doesn't use)
    into the data flow of ``actuals`` so the transfer is neither dead-code-
    eliminated nor dropped from the autodiff graph.

    Reference parity: ChainerMN's ``pseudo_connect`` kept a live autograd
    edge so the send side's ``backward()`` blocked until the gradient
    arrived.  JAX needs no blocking, but an unused ``ppermute`` output
    *would* be DCE'd by XLA — adding a zero-valued dependency preserves it.
    Returns ``actuals`` (single value if one was passed).
    """
    leaves = jax.tree.leaves(delegate)
    tie = jnp.zeros((), dtype=jnp.float32)
    for leaf in leaves:
        tie = tie + jnp.sum(leaf).astype(jnp.float32) * 0.0
    tied = tuple(
        jax.tree.map(lambda a: a + tie.astype(a.dtype), x) for x in actuals
    )
    return tied[0] if len(tied) == 1 else tied
