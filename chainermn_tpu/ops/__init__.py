"""Differentiable communication ops for use inside jitted SPMD code.

Replaces ChainerMN's ``chainermn.functions`` FunctionNode layer
(collective + point-to-point autograd functions) with axis-name-based
wrappers over ``jax.lax`` collectives, whose transpose rules supply the
reversed-direction backward passes the reference wrote by hand.
"""

from .pallas_attention import flash_attention, flash_attention_supported
from .fused import (
    DEFAULT_BUCKET_BYTES,
    PLAN_STRATEGIES,
    build_overlap_schedule,
    flatten_buckets,
    fused_allreduce,
    fused_pmean,
    hierarchical_allreduce,
    overlap_exchange,
    plan_allreduce,
    reduce_scatter_allgather,
    unflatten_buckets,
)
from .collectives import (
    allgather,
    allreduce,
    alltoall,
    bcast,
    gather,
    pmean,
    psum,
    reduce_scatter,
    scatter,
)
from .point_to_point import (
    ppermute,
    pseudo_connect,
    recv,
    send,
    send_recv,
    shift_down,
    shift_up,
)
from .plan_ir import (
    PATTERNS,
    PRIMITIVES,
    LeafDesc,
    PlanProgram,
    PlanStep,
    describe_payload,
    ensure_program,
    enumerate_pattern_programs,
    lower_fsdp_gather,
    lower_moe_all_to_all,
    lower_pipeline_edge,
    lower_ring_permute,
    step,
)

__all__ = [
    "flash_attention", "flash_attention_supported",
    "DEFAULT_BUCKET_BYTES", "PLAN_STRATEGIES", "build_overlap_schedule",
    "flatten_buckets", "fused_allreduce", "fused_pmean",
    "hierarchical_allreduce", "overlap_exchange", "plan_allreduce",
    "reduce_scatter_allgather", "unflatten_buckets",
    "allgather", "allreduce", "alltoall", "bcast", "gather", "pmean",
    "psum", "reduce_scatter", "scatter",
    "ppermute", "pseudo_connect", "recv", "send", "send_recv",
    "shift_down", "shift_up",
    "PATTERNS", "PRIMITIVES", "LeafDesc", "PlanProgram", "PlanStep",
    "describe_payload", "ensure_program", "enumerate_pattern_programs",
    "lower_fsdp_gather", "lower_moe_all_to_all", "lower_pipeline_edge",
    "lower_ring_permute", "step",
]
