"""Pallas flash attention — the hot-op TPU kernel.

The reference's only hand-written device code was CuPy pack/unpack
kernels (``_memory_utility.py``); XLA makes those unnecessary (SURVEY §2
native inventory), so the Pallas budget goes where the FLOPs are:
attention.  This kernel backs the flagship transformer's
``attention="flash"`` path and the per-block math of
:func:`chainermn_tpu.parallel.ring_attention.ring_attention`
(``use_flash=True``).

Design (flash-attention v2 schedule, TPU-shaped):

- 3-D grid ``(B·H, T_q/block_q, T_k/block_k)`` with the K dimension
  innermost and ``arbitrary`` semantics: the Pallas pipeline
  double-buffers each K/V block's HBM→VMEM DMA behind the previous
  block's math, and only ``block_k`` tokens of K/V ever sit in VMEM (so
  context length is bounded by HBM, not the 16 MB of VMEM);
- **online softmax** in fp32 VMEM scratch (running max ``m``,
  normaliser ``l``, accumulator) — no (T, T) score matrix in HBM;
- matmuls via ``jnp.dot(..., preferred_element_type=float32)`` so bf16
  inputs hit the MXU at full rate with fp32 accumulation;
- causal masking in *global* positions: ``q_offset``/``k_offset`` ride
  in SMEM, so they may be **traced values** (ring attention's rotating
  block offsets) — fully-masked K blocks skip their FLOPs via
  ``pl.when``;
- optionally returns the softmax log-sum-exp, with its own VJP path, so
  sequence-sharded callers can combine per-shard partial attentions
  exactly (``o = Σ o_i·exp(lse_i − lse)``);
- backward = two recompute kernels (dq; dk/dv) off the saved lse —
  flash's O(T) memory in the backward too;
- ``interpret=True`` runs the identical kernels on CPU (how the test
  suite exercises them on the virtual pod).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_supported"]

_NEG = -1e30
_LANE = 128  # TPU lane width: trailing dim of lse/delta and vector scratch


def _bcast(vec, n=_LANE):
    return jnp.broadcast_to(vec[:, None], (vec.shape[0], n))


def _positions(off, base, count):
    return off + base + jax.lax.broadcasted_iota(
        jnp.int32, (count, 1), 0)[:, 0]


# --------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------- #


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, l_ref, m_ref, *, scale, causal, window):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    Bq, D = q_ref.shape[1:]
    Bk = k_ref.shape[1]
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        l_ref[...] = jnp.zeros_like(l_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)

    # K blocks entirely in this q block's future contribute nothing
    # Non-causal predicate is a tautology but must stay TRACED: an
    # unconditioned kernel body trips the hlo-interpreter's vma check
    # under shard_map (jax bug); pl.when(cond) routes discharge safely.
    needed = (j >= 0) if not causal else (
        q_off + (i + 1) * Bq - 1 >= k_off + j * Bk)
    if window is not None:
        # also skip K blocks entirely BEFORE the window of every q row
        needed &= (k_off + (j + 1) * Bk - 1
                   >= q_off + i * Bq - (window - 1))

    @pl.when(needed)
    def _():
        # dots take the refs' NATIVE dtype (bf16 in production) with
        # fp32 accumulation — casting operands to fp32 first would run
        # every matmul at the MXU's fp32 rate, ~4x slower (measured:
        # the whole train-step attention share dropped ~2x when these
        # casts were removed); softmax statistics stay fp32 throughout
        q = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        allow = None
        if causal:
            qpos = _positions(q_off, i * Bq, Bq)
            kpos = _positions(k_off, j * Bk, Bk)
            allow = qpos[:, None] >= kpos[None, :]
            if window is not None:
                allow &= (qpos[:, None] - kpos[None, :]) < window
            s = jnp.where(allow, s, _NEG)
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        if allow is not None:
            # explicit zero: for a fully-masked row m_new == _NEG and
            # exp(s - m_new) == 1, which would silently average this
            # block's V rows into the output
            p = jnp.where(allow, p, 0.0)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32)
        l_ref[...] = _bcast(l * alpha + p.sum(axis=-1))
        m_ref[...] = _bcast(m_new)

    @pl.when(j == nk - 1)
    def _():
        l = l_ref[:, 0]
        safe = jnp.maximum(l, 1e-30)   # fully-masked rows stay finite
        o_ref[0] = (acc_ref[...] / safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = _bcast(m_ref[:, 0] + jnp.log(safe))


# --------------------------------------------------------------------- #
# backward (recompute off the saved lse, flash style)
# --------------------------------------------------------------------- #


def _recompute_p(q, kb, scale, lse, causal, window, q_off, k_off, i, j,
                 Bq, Bk):
    s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = _positions(q_off, i * Bq, Bq)
        kpos = _positions(k_off, j * Bk, Bk)
        allow = qpos[:, None] >= kpos[None, :]
        if window is not None:
            allow &= (qpos[:, None] - kpos[None, :]) < window
        s = jnp.where(allow, s, _NEG)
        return jnp.where(allow, jnp.exp(s - lse[:, None]), 0.0)
    return jnp.exp(s - lse[:, None])


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, scale, causal, window):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    Bq, D = q_ref.shape[1:]
    Bk = k_ref.shape[1]
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # Non-causal predicate is a tautology but must stay TRACED: an
    # unconditioned kernel body trips the hlo-interpreter's vma check
    # under shard_map (jax bug); pl.when(cond) routes discharge safely.
    needed = (j >= 0) if not causal else (
        q_off + (i + 1) * Bq - 1 >= k_off + j * Bk)
    if window is not None:
        needed &= (k_off + (j + 1) * Bk - 1
                   >= q_off + i * Bq - (window - 1))

    @pl.when(needed)
    def _():
        # native-dtype (bf16) dot operands, fp32 accumulation — see the
        # forward kernel's note; ds is cast back to the wire dtype for
        # the MXU (the standard flash-v2 backward numerics)
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        kb = k_ref[0]
        vb = v_ref[0]
        p = _recompute_p(q, kb, scale, lse, causal, window, q_off, k_off,
                         i, j, Bq, Bk)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[...] += jnp.dot(ds.astype(kb.dtype), kb,
                               preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, window):
    j, i = pl.program_id(1), pl.program_id(2)   # k block outer, q inner
    nq = pl.num_programs(2)
    Bk, D = k_ref.shape[1:]
    Bq = q_ref.shape[1]
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Non-causal predicate is a tautology but must stay TRACED: an
    # unconditioned kernel body trips the hlo-interpreter's vma check
    # under shard_map (jax bug); pl.when(cond) routes discharge safely.
    needed = (j >= 0) if not causal else (
        q_off + (i + 1) * Bq - 1 >= k_off + j * Bk)
    if window is not None:
        needed &= (k_off + (j + 1) * Bk - 1
                   >= q_off + i * Bq - (window - 1))

    @pl.when(needed)
    def _():
        # native-dtype (bf16) dot operands, fp32 accumulation — see the
        # forward kernel's note; p/ds cast to the wire dtype for the MXU
        kb = k_ref[0]
        vb = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        p = _recompute_p(q, kb, scale, lse, causal, window, q_off, k_off,
                         i, j, Bq, Bk)                   # (Bq, Bk)
        dv_acc[...] += jnp.dot(p.astype(do.dtype).T, do,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[...] += jnp.dot(ds.astype(q.dtype).T, q,
                               preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# pallas_call plumbing
# --------------------------------------------------------------------- #


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _q_spec(block_q, D):
    return pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))


def _k_spec(block_k, D):
    return pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))


def _qvec_spec(block_q):
    return pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0))


def _params():
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-mesh-axes set, so the
    kernel composes under shard_map's check_vma discipline."""
    return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)


def _fwd(q3, k3, v3, offs, scale, causal, window, block_q, block_k,
         interpret):
    BH, Tq, D = q3.shape
    Tk = k3.shape[1]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          window=window),
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[_smem_spec(), _q_spec(block_q, D), _k_spec(block_k, D),
                  _k_spec(block_k, D)],
        out_specs=[_q_spec(block_q, D), _qvec_spec(block_q)],
        out_shape=[
            _sds((BH, Tq, D), q3.dtype, q3),
            _sds((BH, Tq, _LANE), jnp.float32, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=interpret,
    )(offs, q3, k3, v3)
    return o, lse[..., 0]


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q3, k3, v3, offs, scale, causal, window, block_q, block_k,
           bwd_block_q, bwd_block_k, interpret):
    return _fwd(q3, k3, v3, offs, scale, causal, window, block_q,
                block_k, interpret)


def _flash_fwd(q3, k3, v3, offs, scale, causal, window, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret):
    o, lse = _fwd(q3, k3, v3, offs, scale, causal, window, block_q,
                  block_k, interpret)
    return (o, lse), (q3, k3, v3, offs, o, lse)


def _flash_bwd(scale, causal, window, fwd_block_q, fwd_block_k,
               block_q, block_k, interpret, res, cts):
    # the backward kernels tile on their OWN block sizes: dq's q-outer
    # grid and dkv's k-outer revisit pattern have different optimal
    # shapes than the forward (the retune lever bench_attention.py
    # --sweep measures); the fwd blocks arrive first in the nondiff
    # tuple and are unused here
    q3, k3, v3, offs, o, lse = res
    do, dlse = cts
    BH, Tq, D = q3.shape
    Tk = k3.shape[1]
    # d s_ij = p_ij (dp_ij − delta_i) from o's cotangent, plus p_ij·dlse_i
    # from lse's — both fold into one "delta_eff = delta − dlse" term.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # (BH,Tq)
    delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (_LANE,))
    lse3 = jnp.broadcast_to(lse[..., None], lse.shape + (_LANE,))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          window=window),
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[
            _smem_spec(),
            _q_spec(block_q, D), _k_spec(block_k, D), _k_spec(block_k, D),
            _q_spec(block_q, D), _qvec_spec(block_q), _qvec_spec(block_q),
        ],
        out_specs=_q_spec(block_q, D),
        out_shape=_sds((BH, Tq, D), q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_params(),
        interpret=interpret,
    )(offs, q3, k3, v3, do, lse3, delta)

    # k outer / q inner grid: swap the roles of the index maps
    kq_spec = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    qk_spec = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    qkvec_spec = pl.BlockSpec(
        (1, block_q, _LANE), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          window=window),
        grid=(BH, Tk // block_k, Tq // block_q),
        in_specs=[
            _smem_spec(),
            qk_spec, kq_spec, kq_spec, qk_spec, qkvec_spec, qkvec_spec,
        ],
        out_specs=[kq_spec, kq_spec],
        out_shape=[
            _sds((BH, Tk, D), k3.dtype, k3),
            _sds((BH, Tk, D), v3.dtype, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=interpret,
    )(offs, q3, k3, v3, do, lse3, delta)
    d_offs = jnp.zeros(offs.shape, jax.dtypes.float0)
    return dq, dk, dv, d_offs


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(T: int, want: int) -> Optional[int]:
    """Pick the block size for a length-``T`` axis given requested size
    ``want``; ``None`` means "not worth the kernel — fall back to XLA".

    - ``T`` must be sublane-aligned (multiple of 8, the fp32 min tile);
    - ``T <= want``: the whole axis is one block;
    - otherwise: the largest power-of-two block <= ``want`` that tiles
      ``T``.  The search floor is 128 — or ``want`` rounded down to a
      power of two, when the caller explicitly requests smaller blocks —
      because blocks below ~128 rows leave the MXU mostly idle, at which
      point the XLA fallback beats a degenerate kernel launch (so e.g.
      T=1032, 8-aligned but only tileable by 8, reports unsupported).
    """
    if T % 8:
        return None
    want = min(want, T)
    if T <= want:
        return T
    b = 1 << (want.bit_length() - 1)   # round down to a power of two
    floor = min(128, b)                # honor explicitly-small requests
    while b >= floor:
        if T % b == 0:
            return b
        b //= 2
    return None


def flash_attention_supported(T_q: int, T_k: int, block_q: int = 1024,
                              block_k: int = 1024) -> bool:
    """Shapes the kernel handles (callers fall back to XLA otherwise):
    8-aligned lengths that are either a single block or tileable by a
    power-of-two block no smaller than 128 (see :func:`_fit_block`)."""
    return (_fit_block(T_q, block_q) is not None
            and _fit_block(T_k, block_k) is not None)


def flash_attention(q, k, v, *, causal: bool = False, window=None,
                    q_offset=0,
                    k_offset=0, block_q: int = 1024, block_k: int = 1024,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None,
                    return_lse: bool = False, interpret: bool = False):
    """Flash attention over ``(B, T, H, D)`` tensors.

    ``q_offset``/``k_offset`` are *global* position offsets of the local
    blocks for sequence-sharded callers — python ints or traced int
    scalars (they ride to the kernel in SMEM); masking follows global
    positions exactly like
    :func:`...parallel.ring_attention.local_attention`, with one
    deliberate divergence: a query row whose ENTIRE K range is masked
    (when ``k_offset > q_offset``, or with ``window`` when the K range
    lies entirely before the row's window) returns **zeros** and an
    lse of ≈``-1e30``, where the XLA oracle returns the meaningless
    uniform-softmax mean of V.  Zeros/-inf are the correct identities for
    callers that combine per-shard partials via lse.

    With ``return_lse=True`` returns ``(out, lse)`` where ``lse`` is
    ``(B, T, H)`` fp32 — both outputs are differentiable.

    ``bwd_block_q``/``bwd_block_k`` tile the two backward kernels
    independently of the forward (default: the forward blocks) — the
    dq kernel's q-outer grid and the dkv kernel's k-outer revisit
    pattern peak at different shapes, and gradients are exact for any
    valid tiling (``bench_attention.py --sweep`` measures the retune).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding causal "
                         "window attention)")
    if window is not None and window < 1:
        raise ValueError(f"window {window} must be >= 1")
    bq, bk = _fit_block(Tq, block_q), _fit_block(Tk, block_k)
    if bq is None or bk is None:
        raise ValueError(
            f"sequence lengths ({Tq}, {Tk}) unsupported: lengths must be "
            "multiples of 8 and either fit in one block or be tileable "
            "by a power-of-two block >= 128 — gate on "
            "flash_attention_supported() and fall back to "
            "local_attention")
    # a bwd override that doesn't tile THIS shape falls back to the
    # forward blocks rather than erroring: the knob is a perf hint
    # (often adopted from a sweep at another sequence length) and must
    # never turn a supported shape into a trace-time failure
    bwd_bq = (_fit_block(Tq, bwd_block_q) or bq) if bwd_block_q else bq
    bwd_bk = (_fit_block(Tk, bwd_block_k) or bk) if bwd_block_k else bk
    block_q, block_k = bq, bk
    offs = jnp.asarray(
        jnp.stack([jnp.asarray(q_offset, jnp.int32),
                   jnp.asarray(k_offset, jnp.int32)]))
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    o, lse = _flash(to3(q), to3(k), to3(v), offs, D ** -0.5, causal,
                    None if window is None else int(window),
                    block_q, block_k, bwd_bq, bwd_bk, interpret)
    o = o.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    if return_lse:
        return o, lse.reshape(B, H, Tq).transpose(0, 2, 1)
    return o
