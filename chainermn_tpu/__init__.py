"""chainermn_tpu — a TPU-native distributed training framework with the
capabilities of ChainerMN, built from scratch on JAX/XLA (pjit, shard_map,
pallas).  See SURVEY.md for the structural analysis of the reference and
README.md for the design.

Public surface mirrors ``chainermn``'s (create_communicator,
create_multi_node_optimizer, scatter_dataset, ...) re-designed for the
single-controller SPMD model: collectives lower to XLA ops over the ICI/DCN
mesh instead of MPI/NCCL calls.
"""

from chainermn_tpu import ops
from chainermn_tpu.communicators import (
    CommunicatorBase,
    LoopbackCommunicator,
    TpuXlaCommunicator,
    create_communicator,
)

__version__ = "0.1.0"

__all__ = [
    "CommunicatorBase",
    "LoopbackCommunicator",
    "TpuXlaCommunicator",
    "create_communicator",
    "ops",
]
