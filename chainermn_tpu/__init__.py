"""chainermn_tpu — a TPU-native distributed training framework with the
capabilities of ChainerMN, built from scratch on JAX/XLA (pjit, shard_map,
pallas).  See SURVEY.md for the structural analysis of the reference and
README.md for the design.

Public surface mirrors ``chainermn``'s (create_communicator,
create_multi_node_optimizer, scatter_dataset, ...) re-designed for the
single-controller SPMD model: collectives lower to XLA ops over the ICI/DCN
mesh instead of MPI/NCCL calls.
"""

from chainermn_tpu.parallel import _compat  # noqa: F401  (jax shims first)
from chainermn_tpu import (extensions, links, models, ops,
                           parallel, serving, testing, utils)
from chainermn_tpu.extensions import (
    add_global_except_hook,
    create_multi_node_checkpointer,
    multi_node_snapshot,
)
from chainermn_tpu.communicators import (
    CommunicatorBase,
    DataSizeError,
    LoopbackCommunicator,
    TpuXlaCommunicator,
    create_communicator,
    init_distributed,
)
from chainermn_tpu.datasets import (
    create_empty_dataset,
    scatter_dataset,
    scatter_index,
    shuffle_data_blocks,
)
from chainermn_tpu.iterators import (
    DeviceWindow,
    PrefetchIterator,
    SerialIterator,
    StagingConverter,
    create_multi_node_iterator,
    create_synchronized_iterator,
)
from chainermn_tpu.training import (
    Evaluator,
    LogReport,
    PrintReport,
    StandardUpdater,
    Trainer,
    create_multi_node_evaluator,
    create_multi_node_optimizer,
    cross_replica_mean,
    shard_opt_state,
    zero1_init,
    zero1_optimizer,
)

__version__ = "0.1.0"

__all__ = [
    "CommunicatorBase",
    "DataSizeError",
    "DeviceWindow",
    "Evaluator",
    "LogReport",
    "LoopbackCommunicator",
    "PrefetchIterator",
    "PrintReport",
    "SerialIterator",
    "StagingConverter",
    "StandardUpdater",
    "TpuXlaCommunicator",
    "Trainer",
    "create_communicator",
    "create_empty_dataset",
    "create_multi_node_evaluator",
    "create_multi_node_iterator",
    "create_multi_node_optimizer",
    "create_synchronized_iterator",
    "init_distributed",
    "add_global_except_hook",
    "create_multi_node_checkpointer",
    "cross_replica_mean",
    "shard_opt_state",
    "zero1_init",
    "zero1_optimizer",
    "extensions",
    "links",
    "multi_node_snapshot",
    "models",
    "ops",
    "parallel",
    "utils",
    "scatter_dataset",
    "scatter_index",
    "serving",
    "shuffle_data_blocks",
    "testing",
]
