"""Elastic resume — topology signatures, shrink/grow re-layout of
sharded train state, and membership epochs on the coordination store.

PR 3's resilience layer assumed a fixed world: resume demanded the same
topology, and a preempted fleet that came back smaller simply could not
use its own snapshots.  This module is the missing spine
(docs/RESILIENCE.md "Elastic resume"):

- :func:`topology_signature` — the layout a snapshot was written under:
  world size (mesh members), process count, mesh shape/axis names, and
  the per-leaf shard layout of every ZeRO-1 optimizer-state leaf
  ("Automatic Cross-Replica Sharding of Weight Update", PAPERS.md
  2004.13336 — the layout that must survive a resize).  Stamped into
  every shard's ``__meta__`` (``utils/serialization.py``) and into the
  state dict itself.
- :func:`relayout_state` — the deterministic re-slicing of a saved
  state onto a new world size W′ ≠ W, following the memory-efficient
  array-redistribution formulation (PAPERS.md 2112.01075) in its
  host-side form: each world-stacked ZeRO-1 shard leaf is concatenated
  back to its true flat extent (the minimal covering read — padding
  never travels), re-padded for W′ and re-split, so the result is
  BITWISE what a from-scratch sharding of the gathered state at W′
  would hold.  Replicated leaves pass through untouched; the
  snapshot-riding exchange plan is dropped (the plan cache is keyed by
  topology, so resume re-tunes rather than replaying a stale program).
- :class:`ElasticMembership` — epoch-numbered membership records:
  survivors of a preemption agree a new world size + rank assignment
  collectively (over the coordination-service KV store only — the
  data plane may be the thing that died) BEFORE any process touches
  the snapshot set, and :meth:`ElasticMembership.fence` tags every
  object channel with the agreed epoch so stale-generation traffic
  from the previous incarnation is rejected
  (:class:`~chainermn_tpu.communicators._obj_channel.StaleGenerationError`).

The consumer is ``MultiNodeCheckpointer(..., elastic=True)``: on resume
it compares the stamped signature against the live topology and enters
the re-layout path only on a mismatch — a same-topology resume stays on
the exact (bitwise) path and never re-slices anything.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from chainermn_tpu.communicators._obj_channel import (
    KVObjectChannel,
    StaleGenerationError,
)

_LOG = logging.getLogger(__name__)

__all__ = [
    "ElasticMembership",
    "MembershipRecord",
    "RelayoutError",
    "ResizeController",
    "StaleGenerationError",
    "TOPOLOGY_FORMAT",
    "gather_zero1_leaves",
    "post_resize_intent",
    "relayout_state",
    "same_topology",
    "shard_zero1_leaves",
    "topology_signature",
]

# Bump when the signature's meaning changes: a format mismatch is a
# topology mismatch (conservative — the re-layout path validates, the
# exact path must never silently trust a record it cannot read).
TOPOLOGY_FORMAT = 1

# The scalar fields two signatures must agree on to count as the SAME
# topology (the per-leaf layouts are derived from these + the tree).
_COMPARE_KEYS = ("format", "world_size", "inter_size", "axis_names",
                 "mesh_shape", "zero1")


class RelayoutError(RuntimeError):
    """A saved state could not be deterministically re-laid onto the new
    topology (missing/garbled layout record, a leaf the signature cannot
    identify, zero1-mode mismatch).  Typed so the checkpointer can
    distinguish "this resize is unsafe" from file corruption."""


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# --------------------------------------------------------------------- #
# topology signatures
# --------------------------------------------------------------------- #

def _leaf_paths(tree) -> List[tuple]:
    from jax.tree_util import tree_flatten_with_path

    paths, _ = tree_flatten_with_path(tree)
    return paths


def _zero1_leaf_layout(opt_state, params, world: int) -> List[dict]:
    """Per-leaf layout records for a world-stacked ZeRO-1/2 state tree,
    in flattened-leaf order — since PR 20 a thin delegate to the
    unified signature table (``parallel.sharded_state``), which emits
    the IDENTICAL records this function always wrote:

    - ``{"kind": "shard", "size": N}`` — a ``(world, ceil(N/world))``
      stack of 1-D parameter shards (``zero1_optimizer``'s
      ``_leaf_shard`` layout); ``N`` is the mirrored parameter's true
      element count, identified by the same longest-path-suffix match
      ``shard_opt_state`` uses (``mu.blocks.w`` ↔ ``blocks.w``).
    - ``{"kind": "stack"}`` — a leading member axis over per-member
      replicas (adam's ``count``): every row identical by construction.
    - ``{"kind": "rep"}`` — no member axis at all (replicated scalar).
    """
    from chainermn_tpu.parallel.sharded_state import (
        layout_records,
        zero_opt_layouts,
    )

    return layout_records(zero_opt_layouts(opt_state, params, world))


def _sharding_mode(sig: Optional[dict]) -> Optional[str]:
    """The normalized sharding mode of a signature: the explicit
    ``sharding`` key when stamped (PR 20+), else the legacy ``zero1``
    bool — so old ZeRO-1 snapshots compare equal to new ones."""
    if sig is None:
        return None
    mode = sig.get("sharding")
    if mode is not None:
        return str(mode)
    return "zero1" if sig.get("zero1") else None


def topology_signature(comm, params=None, opt_state=None,
                       zero1: bool = False,
                       sharding: Optional[str] = None,
                       layouts: Optional[dict] = None) -> dict:
    """The JSON-safe layout record a snapshot is stamped with.

    ``world_size`` is the mesh-member count (``comm.size`` — the axis
    ZeRO shards over), ``inter_size`` the process count.  ``sharding``
    names the state-sharding mode (``"zero1"``/``"zero2"``/``"zero3"``;
    the legacy ``zero1`` bool still works and means ``"zero1"``).  With
    a ZeRO mode and both trees given, ``opt_leaves`` records every
    optimizer-state leaf's shard layout so :func:`relayout_state` can
    re-slice it onto a different world deterministically; a ``layouts``
    table (``parallel.sharded_state.state_layout_table``'s output)
    overrides the derivation and — for ``"zero3"`` — additionally
    stamps ``param_leaves`` so the shard-only snapshot container can
    slice dim-sharded params too."""
    mode = sharding if sharding is not None else (
        "zero1" if zero1 else None)
    mesh = getattr(comm, "mesh", None)
    sig = {
        "format": TOPOLOGY_FORMAT,
        "world_size": int(getattr(comm, "size", 1)),
        "inter_size": int(getattr(comm, "inter_size", 1)),
        "axis_names": (list(mesh.axis_names) if mesh is not None
                       else None),
        "mesh_shape": ([int(s) for s in np.asarray(mesh.devices).shape]
                       if mesh is not None else None),
        # legacy key: True for any world-stacked ZeRO carry, so a
        # pre-PR-20 reader treats ZeRO-2 state with the ZeRO-1 rules
        # (they are the same layout)
        "zero1": mode in ("zero1", "zero2"),
    }
    if mode is not None:
        sig["sharding"] = mode
    if layouts is not None:
        from chainermn_tpu.parallel.sharded_state import layout_records

        if layouts.get("opt_state") is not None:
            sig["opt_leaves"] = layout_records(layouts["opt_state"])
        recs = layout_records(layouts.get("params") or [])
        if any(r.get("kind") == "fsdp" for r in recs):
            sig["param_leaves"] = recs
    elif mode in ("zero1", "zero2") and params is not None \
            and opt_state is not None:
        sig["opt_leaves"] = _zero1_leaf_layout(
            opt_state, params, sig["world_size"])
    return sig


def same_topology(a: Optional[dict], b: Optional[dict]) -> bool:
    """Whether two signatures describe the SAME topology (the exact
    bitwise resume path).  ``None`` (a pre-elastic snapshot) never
    matches — the caller decides whether legacy rules apply.  The
    sharding mode is compared NORMALIZED (:func:`_sharding_mode`), so
    a pre-PR-20 ZeRO-1 signature still matches a new one."""
    if a is None or b is None:
        return False
    return (all(a.get(k) == b.get(k) for k in _COMPARE_KEYS)
            and _sharding_mode(a) == _sharding_mode(b))


# --------------------------------------------------------------------- #
# shrink/grow re-layout
# --------------------------------------------------------------------- #

def _rows_identical(arr: np.ndarray) -> bool:
    first = arr[:1]
    return all(arr[i:i + 1].tobytes() == first.tobytes()
               for i in range(1, arr.shape[0]))


def _relayout_leaf(leaf, spec: dict, new_world: int, where: str):
    arr = np.asarray(leaf)
    kind = spec.get("kind")
    if kind == "rep":
        return arr
    if kind == "shard":
        if arr.ndim != 2:
            raise RelayoutError(
                f"{where}: recorded as a shard stack but has shape "
                f"{arr.shape} — the snapshot's layout record does not "
                "describe this tree")
        size = int(spec["size"])
        flat = arr.reshape(-1)
        if flat.size < size:
            raise RelayoutError(
                f"{where}: shard stack holds {flat.size} elements, "
                f"fewer than the recorded parameter size {size}")
        s2 = _ceil_div(size, new_world)
        out = np.zeros((new_world * s2,), dtype=arr.dtype)
        # the minimal covering read: only the true extent travels, the
        # old padding is dropped and fresh zero padding is laid exactly
        # where a from-scratch sharding at new_world would put it
        out[:size] = flat[:size]
        return out.reshape(new_world, s2)
    if kind == "stack":
        if arr.ndim < 1 or arr.shape[0] < 1:
            raise RelayoutError(f"{where}: empty member stack")
        if not _rows_identical(arr):
            raise RelayoutError(
                f"{where}: member-stacked leaf rows differ but the "
                "layout record did not identify it as a parameter "
                "shard — refusing to re-slice state whose layout is "
                "unknown (a silent slice would corrupt the optimizer)")
        if new_world <= arr.shape[0]:
            return arr[:new_world]
        reps = [arr] + [arr[:1]] * (new_world - arr.shape[0])
        return np.concatenate(reps, axis=0)
    if kind == "fsdp":
        # ZeRO-3 dim-sharded leaf: host-side state is FULL-width (the
        # shard-only container reassembles it before re-layout), so
        # re-laying onto a new world is a pass-through — the device
        # placement at the new world re-slices the dim.  Validate the
        # recorded extent so a sliced leaf cannot slip through as full.
        dim = int(spec.get("dim", -1))
        length = spec.get("len")
        if dim < 0 or dim >= arr.ndim:
            raise RelayoutError(
                f"{where}: fsdp layout records shard dim {dim} but the "
                f"leaf has shape {arr.shape}")
        if length is not None and int(arr.shape[dim]) != int(length):
            raise RelayoutError(
                f"{where}: fsdp leaf holds {arr.shape[dim]} of the "
                f"recorded {length} elements along dim {dim} — a "
                "shard, not the assembled full leaf; assemble the "
                "covering set first (assemble_shard_state)")
        return arr
    raise RelayoutError(f"{where}: unknown layout kind {kind!r}")


def relayout_state(state: dict, topo_old: dict, topo_new: dict) -> dict:
    """Re-lay a checkpointer state dict saved under ``topo_old`` onto
    ``topo_new``'s world size.  Deterministic and host-side: every rank
    computes the identical result from the same shard bytes.

    Replicated entries (``params``, ``model_state``) pass through;
    world-stacked ZeRO-1 optimizer state is re-sliced per its recorded
    layout (bitwise-equal to a from-scratch sharding of the gathered
    state at the new world — the drill in
    ``tests/extension_tests/test_elastic_checkpoint.py`` pins this);
    the snapshot-riding exchange plan is dropped so resume re-tunes for
    the new topology instead of replaying a stale program."""
    mode_old = _sharding_mode(topo_old)
    mode_new = _sharding_mode(topo_new)
    if mode_old != mode_new:
        raise RelayoutError(
            f"snapshot was saved with sharding={mode_old!r} but this "
            f"job runs sharding={mode_new!r} — elastic resume re-lays "
            "a sharding, it does not convert between layouts")
    new_world = int(topo_new["world_size"])
    out = dict(state)
    if mode_old is not None:
        layouts = topo_old.get("opt_leaves")
        if layouts is None:
            raise RelayoutError(
                f"snapshot records sharding={mode_old!r} but carries "
                "no per-leaf layout — it predates the elastic-resume "
                "format and can only restart at its original topology")
        import jax
        from jax.tree_util import keystr, tree_flatten_with_path

        path_leaves, treedef = tree_flatten_with_path(
            state["opt_state"])
        if len(path_leaves) != len(layouts):
            raise RelayoutError(
                f"snapshot records {len(layouts)} optimizer-state "
                f"leaves but the tree holds {len(path_leaves)} — the "
                "model changed shape as well as the world; elastic "
                "resume only re-lays the same model")
        # the leaf PATH rides every error: "opt_state['mu']['w1']
        # recorded as a shard stack but..." beats "leaf 17"
        new_leaves = [
            _relayout_leaf(leaf, spec, new_world,
                           f"opt_state{keystr(path)}")
            for (path, leaf), spec in zip(path_leaves, layouts)]
        out["opt_state"] = jax.tree.unflatten(treedef, new_leaves)
    ts = state.get("train_state")
    if isinstance(ts, dict) and "exchange_plan" in ts:
        ts = dict(ts)
        ts.pop("exchange_plan")
        out["train_state"] = ts
        _LOG.info(
            "elastic resume: dropped the snapshot-riding exchange plan "
            "(tuned for world=%s) — the new topology re-tunes",
            topo_old.get("world_size"))
    return out


# one-time (per process) deprecation notice for the ZeRO-1-named
# gather/shard entry points — the unified layer replaced them in PR 20
_ZERO1_LEAVES_WARNED = False


def _warn_zero1_leaves_deprecated(name: str) -> None:
    global _ZERO1_LEAVES_WARNED
    if _ZERO1_LEAVES_WARNED:
        return
    _ZERO1_LEAVES_WARNED = True
    import warnings

    warnings.warn(
        f"training.elastic.{name} is deprecated: the unified "
        "sharded-state layer (parallel.sharded_state."
        "gather_state_leaves / shard_state_leaves) handles "
        "ZeRO-1/2/3 layouts through one signature table; this shim "
        "delegates there and will be removed (warning shown once per "
        "process)", DeprecationWarning, stacklevel=3)


def gather_zero1_leaves(opt_state, layouts: List[dict]):
    """Deprecated shim: gather a world-stacked ZeRO-1/2 state tree to
    its full flat values — delegates to the unified
    :func:`chainermn_tpu.parallel.sharded_state.gather_state_leaves`
    (identical behavior for ``shard``/``stack``/``rep`` records; the
    unified layer additionally speaks ``fsdp``).  PR 10/12 call sites
    keep working unchanged; warns once per process."""
    from chainermn_tpu.parallel.sharded_state import gather_state_leaves

    _warn_zero1_leaves_deprecated("gather_zero1_leaves")
    return gather_state_leaves(opt_state, layouts)


def shard_zero1_leaves(full_state, layouts: List[dict], world: int):
    """Deprecated shim: inverse of :func:`gather_zero1_leaves` —
    delegates to the unified
    :func:`chainermn_tpu.parallel.sharded_state.shard_state_leaves`,
    the reference layout :func:`relayout_state` must match bitwise.
    Warns once per process."""
    from chainermn_tpu.parallel.sharded_state import shard_state_leaves

    _warn_zero1_leaves_deprecated("shard_zero1_leaves")
    return shard_state_leaves(full_state, layouts, world)


# --------------------------------------------------------------------- #
# membership epochs
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class MembershipRecord:
    """One agreed membership epoch: who is in the world and in what
    order.  ``members`` is the sorted list of surviving process ids;
    a process's new rank is its index in that list."""

    epoch: int
    world_size: int
    members: List[int]
    created: float = 0.0

    def rank_of(self, process_id: int) -> int:
        return self.members.index(process_id)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "MembershipRecord":
        return cls(epoch=int(d["epoch"]),
                   world_size=int(d["world_size"]),
                   members=[int(m) for m in d["members"]],
                   created=float(d.get("created", 0.0)))


class ElasticMembership:
    """Epoch-numbered membership agreement over the coordination store.

    Protocol (``agree()``, collective over the CURRENT incarnation's
    processes): every survivor contributes ``(process_id,
    last_known_epoch)`` through a KV-only allgather — deliberately not
    an XLA collective, because membership must be agreeable exactly
    when the data plane is the thing that died — and every process
    folds the same rows into the same record: members = the sorted
    contributor ids, epoch = max(previous epochs) + 1.  The first
    member persists the record beside the snapshots (``path``, atomic
    write) so epochs survive relaunch, and publishes it on the KV
    store (``elastic/membership/<epoch>``) for tooling.  Only after
    ``agree()`` returns does anyone touch the snapshot set — the
    checkpointer's re-layout path then maps the agreed world onto the
    saved shards.

    ``fence(...)`` tags object channels with the agreed epoch
    (:meth:`KVObjectChannel.set_generation`): traffic from a previous
    incarnation that survived on the store is then rejected with
    :class:`StaleGenerationError` instead of being consumed by the
    resized world.

    ``PreemptionCheckpointer(..., membership=...)`` feeds the cycle:
    on the preemption notice it records the stop (``note_stop``) after
    the collective save, so the relaunch — at whatever world size the
    scheduler grants — bumps the epoch past every incarnation that ever
    wrote a snapshot.

    Bootstrap contract: ``agree()`` itself necessarily runs BEFORE any
    epoch is agreed, so its own allgather cannot be generation-fenced.
    Between-run relaunches are safe because ``jax.distributed`` re-init
    hands every incarnation a FRESH coordination store (a dead world's
    keys do not survive into the new one) plus per-process incarnation-
    salted channel tags for repeated agreements within one store.  A
    future WITHIN-run resize over a store that outlives its world (the
    ROADMAP item) must additionally salt the bootstrap tag with an
    incarnation identity survivors already share — e.g. the snapshot
    directory's persisted epoch — before this protocol is safe there.
    """

    KV_PREFIX = "elastic"

    # per-process creation counter: distinct ElasticMembership objects
    # must not share KV lanes (their allgather sequence numbers restart
    # at 0).  SPMD-consistent because every process constructs its
    # memberships in the same order — the same program-identity
    # discipline the communicators already assume.
    _INCARNATIONS = 0

    def __init__(self, comm, path: Optional[str] = None,
                 filename: str = "membership.json",
                 timeout_ms: int = 60_000):
        self.comm = comm
        self.path = path
        self.filename = filename
        self.record: Optional[MembershipRecord] = None
        inc = ElasticMembership._INCARNATIONS
        ElasticMembership._INCARNATIONS = inc + 1
        self._channel = KVObjectChannel(
            tag=f"elastic-membership-i{inc}", timeout_ms=timeout_ms)

    # -- persistence --------------------------------------------------- #

    @property
    def _file(self) -> Optional[str]:
        if self.path is None:
            return None
        return os.path.join(self.path, self.filename)

    def _read_file(self) -> dict:
        f = self._file
        if f is None:
            return {}
        try:
            with open(f) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return {}

    def _write_file(self, payload: dict) -> None:
        f = self._file
        if f is None:
            return
        os.makedirs(os.path.dirname(f) or ".", exist_ok=True)
        tmp = f"{f}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        os.replace(tmp, f)

    def stored_epoch(self) -> int:
        """The newest epoch this process can see locally (the persisted
        record; 0 when none exists — the first incarnation agrees
        epoch 1)."""
        return int(self._read_file().get("epoch", 0))

    # -- the KV side --------------------------------------------------- #

    @property
    def _kv(self):
        """Coordination-service client, or ``None`` outside a
        multi-process world (single-controller jobs need no KV)."""
        if int(getattr(self.comm, "inter_size", 1)) <= 1:
            return None
        from jax._src import distributed

        return distributed.global_state.client

    def _publish_record(self, rec: MembershipRecord) -> None:
        kv = self._kv
        if kv is None:
            return
        from chainermn_tpu.communicators._obj_channel import kv_overwrite

        payload = json.dumps(rec.to_dict(), sort_keys=True)
        for key, value in ((f"{self.KV_PREFIX}/epoch", str(rec.epoch)),
                           (f"{self.KV_PREFIX}/membership/{rec.epoch}",
                            payload)):
            try:
                kv_overwrite(kv, key, value)
            except Exception:
                pass    # best-effort exposition; the file is durable

    # -- the collective ------------------------------------------------ #

    def agree(self) -> MembershipRecord:
        """Agree this incarnation's membership record (COLLECTIVE: every
        surviving process must call).  Returns the record; also stored
        as :attr:`record`."""
        me = int(getattr(self.comm, "inter_rank", 0))
        n = int(getattr(self.comm, "inter_size", 1))
        prev = self.stored_epoch()
        if n <= 1:
            rows = [(me, prev)]
        else:
            rows = self._channel.allgather(
                (me, prev), list(range(n)), me)
        members = sorted(int(r) for r, _ in rows)
        epoch = max(int(p) for _, p in rows) + 1
        rec = MembershipRecord(epoch=epoch, world_size=len(members),
                               members=members, created=time.time())
        if me == members[0]:
            self._write_file(rec.to_dict())
            self._publish_record(rec)
        self.record = rec
        _LOG.info(
            "elastic membership epoch %d agreed: world_size=%d "
            "members=%s (this process: rank %d)",
            epoch, rec.world_size, members, rec.rank_of(me))
        return rec

    def fence(self, *targets) -> int:
        """Fence object channels to the agreed epoch.  Each target is a
        :class:`KVObjectChannel` or anything carrying one as
        ``_obj_channel`` (a communicator).  Returns the generation
        set.  Must run AFTER :meth:`agree`."""
        if self.record is None:
            raise RuntimeError(
                "fence() before agree() — there is no agreed epoch to "
                "fence to")
        gen = self.record.epoch
        for t in targets:
            chan = getattr(t, "_obj_channel", t)
            if not hasattr(chan, "set_generation"):
                raise TypeError(
                    f"cannot fence {type(t).__name__}: no object "
                    "channel found")
            chan.set_generation(gen)
        return gen

    def note_stop(self, reason: str = "",
                  iteration: Optional[int] = None) -> None:
        """Record that this incarnation stopped deliberately (the
        preemption path calls this after its collective save), so the
        relaunch's ``agree()`` bumps past this epoch even on a fresh
        coordination service.  First member writes; others no-op."""
        me = int(getattr(self.comm, "inter_rank", 0))
        writer = (self.record.members[0] if self.record is not None
                  else 0)
        if me != writer:
            return
        if self._file is None:
            _LOG.warning(
                "ElasticMembership.note_stop: no durable path was "
                "configured (path=None), so this stop is NOT recorded "
                "— a relaunch cannot bump the epoch past this "
                "incarnation; pass path=<snapshot dir> to get the "
                "documented preemption→relaunch cycle")
            return
        payload = self._read_file()
        if self.record is not None:
            payload.update(self.record.to_dict())
        payload.setdefault("epoch", self.stored_epoch())
        payload["stopped"] = {"reason": reason, "iteration": iteration,
                              "ts": time.time()}
        self._write_file(payload)


# --------------------------------------------------------------------- #
# live in-run resize
# --------------------------------------------------------------------- #

#: KV prefix a resize intent is posted under (`post_resize_intent`).
RESIZE_KV_PREFIX = "elastic/resize"


def post_resize_intent(world_size: int, reason: str = "") -> None:
    """Post a resize intent on the coordination-service KV store for a
    running job's :class:`ResizeController` to pick up (external
    tooling's entry point; in-process callers can use
    ``controller.request`` directly).  Overwrite-in-place, so repeated
    posts converge on the newest intent."""
    from jax._src import distributed

    from chainermn_tpu.communicators._obj_channel import kv_overwrite

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "post_resize_intent needs the JAX distributed runtime "
            "(init_distributed) — single-controller jobs call "
            "ResizeController.request instead")
    kv_overwrite(client, f"{RESIZE_KV_PREFIX}/intent",
                 json.dumps({"world_size": int(world_size),
                             "reason": reason, "ts": time.time()}))


class ResizeController:
    """Trainer extension: resize a LIVE job at a step boundary —
    training continues in the same processes, no restart.

    The between-run path (PR 10) pays a full save + relaunch; this
    controller performs the identical state transformation IN PLACE:

    1. **Intent** — ``request(world)`` (host-side), a KV-posted intent
       (:func:`post_resize_intent`, for external tooling), or whatever
       arms the flag from a signal handler.  Every tick (on the shared
       ``check_interval`` cadence, exactly the
       ``PreemptionCheckpointer`` discipline) the locally-seen intent
       is OR-agreed across processes, so every rank pauses at the SAME
       step boundary — conflicting concurrent intents resolve to the
       largest world.
    2. **Pause** — extensions run between steps, so the boundary is
       free; in-flight dispatched windows are drained first.
    3. **State out** — the exact checkpointer state dict is collected
       and copied to host (collective gather for process-spanning
       leaves), stamped with the OLD topology signature.
    4. **Epoch** — ``membership.agree()`` bumps the epoch and
       ``fence()`` rolls channel generations so pre-resize traffic is
       rejected (:class:`StaleGenerationError`); without a membership,
       a local epoch counter still increments.  Serving engines passed
       in ``drain_engines`` are drained BEFORE the world moves
       (admission stops, active rows retire or timeout-evict) — see
       docs/SERVING.md "Epoch drains".
    5. **Re-form** — ``comm_factory(world)`` builds the new
       communicator over the surviving in-process devices (the
       8-device CPU mesh shrink/grow is the tested path; re-forming a
       mesh across a CHANGED process set — and redistributing with
       real collectives instead of the host-side pass — stays
       TPU-gated), ``optimizer_factory(new_comm)`` the new optimizer.
    6. **Re-lay** — :func:`relayout_state` re-slices the saved state
       onto the new world (bitwise what a save/restart at this
       boundary would restore; same topology skips it), the step cache
       and snapshot-riding exchange plan are dropped so the new world
       re-tunes, and ``updater.rebind_world`` installs everything.
       Training continues with the next ``update()``.

    ``on_resize(controller, new_comm, epoch)`` (optional) runs last —
    the hook where a serving fleet rebuilds its engines under the new
    epoch and re-imports its carried-over queue
    (``ServingEngine.export_queue`` / ``import_queue``).
    """

    trigger = (1, "iteration")
    # priority 0: the VERY last extension on its tick — log writers,
    # checkpointers and fault injectors all land before the world
    # changes, so a resize at iteration N is indistinguishable from a
    # stop-after-N (the trajectory-equivalence drills pin this)
    priority = 0

    def __init__(self, comm_factory, optimizer_factory, *,
                 membership: Optional[ElasticMembership] = None,
                 coord_comm=None, check_interval: int = 1,
                 drain_engines=(), drain_timeout: Optional[float] = None,
                 fence_targets=(), on_resize=None):
        self.comm_factory = comm_factory
        self.optimizer_factory = optimizer_factory
        self.membership = membership
        self.coord_comm = coord_comm
        self._check_interval = max(int(check_interval), 1)
        self.drain_engines = tuple(drain_engines)
        self.drain_timeout = drain_timeout
        self.fence_targets = tuple(fence_targets)
        self.on_resize = on_resize
        self.epoch = 0              # local counter without a membership
        self._requested: Optional[int] = None
        self._calls = 0
        self.resizes: List[dict] = []
        self.drained: List[Any] = []

    # -- introspection --------------------------------------------------- #

    def status(self) -> dict:
        """The live-elastic block for a ``/statusz`` surface
        (``StatuszServer.add_section("resize", controller)``): the
        membership epoch the job currently runs under, any pending
        intent, and the resize history — so an operator sees a resize
        land (epoch bump, pause cost) without grepping logs."""
        epoch = self.resizes[-1]["epoch"] if self.resizes \
            else self.epoch
        if self.membership is not None:
            try:
                epoch = max(epoch, self.membership.stored_epoch())
            except Exception:   # noqa: BLE001 — introspection only
                pass
        return {
            "epoch": epoch,
            "requested_world": self._requested,
            "resizes": len(self.resizes),
            "last_resize": (dict(self.resizes[-1]) if self.resizes
                            else None),
            "draining_engines": len(self.drain_engines),
        }

    # -- intent ---------------------------------------------------------- #

    def request(self, world_size: int) -> None:
        """Arm a resize to ``world_size`` — acted on at the next step
        boundary on the shared cadence (signal-handler safe: only sets
        a flag)."""
        if int(world_size) < 1:
            raise ValueError(f"world_size={world_size} must be >= 1")
        self._requested = int(world_size)

    def _kv(self, comm):
        if int(getattr(comm, "inter_size", 1)) <= 1:
            return None
        from jax._src import distributed

        return distributed.global_state.client

    def _kv_intent(self, comm) -> Optional[int]:
        kv = self._kv(comm)
        if kv is None:
            return None
        try:
            rows = kv.key_value_dir_get(f"{RESIZE_KV_PREFIX}/")
        except Exception:
            return None             # no intent posted (or flaky store)
        for key, value in rows:
            if key.rstrip("/").endswith("intent"):
                try:
                    return int(json.loads(value)["world_size"])
                except (ValueError, KeyError, TypeError):
                    _LOG.warning(
                        "ignoring malformed resize intent %r", value)
        return None

    def _clear_kv_intent(self, comm) -> None:
        kv = self._kv(comm)
        if kv is None:
            return
        try:
            kv.key_value_delete(f"{RESIZE_KV_PREFIX}/intent")
        except Exception:
            pass                    # best-effort; overwrite converges

    # -- the extension --------------------------------------------------- #

    def __call__(self, trainer) -> None:
        self._calls += 1
        # shared cadence only: every process must make the same
        # enter/skip decision for the agreement allgather below (the
        # PreemptionCheckpointer contract)
        if self._calls % self._check_interval:
            return
        comm = self.coord_comm or trainer.updater.comm
        mine = self._requested
        if mine is None:
            mine = self._kv_intent(comm)
        if int(getattr(comm, "inter_size", 1)) > 1:
            rows = comm.allgather_obj(mine)
            seen = [r for r in rows if r is not None]
            agreed = max(seen) if seen else None
        else:
            agreed = mine
        if agreed is None:
            return
        self.resize(trainer, agreed)

    # -- the resize ------------------------------------------------------ #

    def resize(self, trainer, world_size: int) -> None:
        """Perform the live resize NOW (normally reached through the
        agreed intent; callable directly in single-controller jobs and
        drills)."""
        from chainermn_tpu.training._resume import (
            collect_train_state,
            restore_train_state,
        )
        from chainermn_tpu.utils.metrics import get_registry
        from chainermn_tpu.utils.serialization import _host_view
        from chainermn_tpu.utils.telemetry import get_recorder

        import jax

        upd = trainer.updater
        it = int(upd.iteration)
        t0 = time.time()
        with get_recorder().span("elastic/live_resize", cat="elastic",
                                 step=it, world=int(world_size)):
            # 0. consume the intent FIRST, on EVERY rank (the KV delete
            #    is idempotent).  The clear must precede the resize's
            #    collectives: were it deferred to the end, a fast rank
            #    could finish, reach its next cadence tick, and re-read
            #    the still-posted intent while a slow rank is mid-
            #    relayout — and the OR-agreement would force a duplicate
            #    resize (spurious epoch bump, re-fence, serving drain)
            #    on everyone.  An operator intent posted DURING the
            #    resize may be consumed with it; repost after the epoch
            #    bump.
            self._requested = None
            self._clear_kv_intent(self.coord_comm or upd.comm)
            # 1. drain: the old mesh's in-flight windows must retire
            #    before its buffers are abandoned
            for pending in list(upd._inflight):
                jax.block_until_ready(pending)
            for eng in self.drain_engines:
                self.drained.extend(
                    eng.drain(timeout=self.drain_timeout))
            # 2. state out, stamped with the OLD topology (exactly the
            #    checkpointer's save dict — the trajectory-equivalence
            #    contract: live resize == save/restart at this boundary)
            topo_old = topology_signature(
                upd.comm, params=upd.params, opt_state=upd.opt_state,
                zero1=bool(getattr(upd, "zero1", False)),
                sharding=getattr(upd, "sharding", None))
            state = {
                "iteration": it,
                "world_size": int(getattr(upd.comm, "inter_size", 1)),
                "params": upd.params,
                "opt_state": upd.opt_state,
                "train_state": collect_train_state(upd, trainer),
            }
            if getattr(upd, "state", None) is not None:
                state["model_state"] = upd.state
            state = jax.tree.map(
                np.array,
                jax.device_get(jax.tree.map(_host_view, state)))
            # 3. epoch: agree membership (KV-only collective — the data
            #    plane may be mid-reconfiguration) and fence channels
            if self.membership is not None:
                rec = self.membership.agree()
                epoch = rec.epoch
            else:
                self.epoch += 1
                epoch = self.epoch
            # 4. re-form the mesh + optimizer over the survivors
            new_comm = self.comm_factory(int(world_size))
            new_opt = self.optimizer_factory(new_comm)
            if self.membership is not None:
                targets = [t for t in (new_comm, *self.fence_targets)
                           if hasattr(
                               getattr(t, "_obj_channel", t),
                               "set_generation")]
                if targets:
                    self.membership.fence(*targets)
            # 5. re-lay the state for the new world (bitwise the
            #    save/restart path: relayout only on a real topology
            #    change, exchange plan dropped so the new world
            #    re-tunes)
            topo_new = topology_signature(
                new_comm, params=state["params"],
                opt_state=state["opt_state"],
                zero1=bool(getattr(upd, "zero1", False)),
                sharding=getattr(upd, "sharding", None))
            if not same_topology(topo_old, topo_new):
                state = relayout_state(state, topo_old, topo_new)
            # 6. install and continue in the same process
            upd.rebind_world(new_comm, new_opt)
            upd.params = state["params"]
            upd.opt_state = state["opt_state"]
            if "model_state" in state:
                upd.state = state["model_state"]
            restore_train_state(state.get("train_state"), upd, trainer)
            # every registered extension still holding the old world's
            # communicator follows (checkpointers stamp topology and
            # write shard-only part sets with THEIR comm — a stale one
            # would label post-resize saves with the pre-resize world).
            # Any in-flight async write is joined/agreed under the old
            # comm inside the extension's own rebind.
            for entry in getattr(trainer, "_extensions", []):
                hook = getattr(entry.ext, "rebind_world", None)
                if hook is not None and entry.ext is not self:
                    hook(new_comm)
            if self.on_resize is not None:
                self.on_resize(self, new_comm, epoch)
        pause = time.time() - t0
        self.resizes.append({"iteration": it, "world": int(world_size),
                             "epoch": epoch, "pause_s": pause})
        get_registry().inc("elastic/live_resizes")
        _LOG.info(
            "live resize at iteration %d: world -> %d (epoch %d, "
            "pause %.3fs) — training continues in-process",
            it, world_size, epoch, pause)
