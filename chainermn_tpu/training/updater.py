"""StandardUpdater — the jitted data-parallel train step.

Replaces the reference's ``Updater → optimizer.update(lossfun) →
loss.backward() → comm.multi_node_mean_grad(model)`` hot loop (SURVEY §3.1)
with its TPU shape: ONE jitted SPMD program per step containing forward,
backward, cross-replica grad mean, and the optimiser update — so XLA can
fuse and overlap the collective with compute (what pure_nccl needed streams
and double-buffer threads for).

The global batch enters sharded over the communicator's mesh axis; params
stay replicated; optimiser state is replicated too, EXCEPT under ZeRO-1
(detected from the transformation type), where it is carried
world-stacked and sharded over the axis; the ``multi-node optimizer``'s
``cross_replica_mean`` supplies the ``pmean``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["StandardUpdater", "default_converter", "fuse_steps"]


def fuse_steps(step_fn, n_steps: int, *, scan_batches: bool = False,
               unroll: int = 1):
    """Fuse ``n_steps`` training steps into ONE XLA program.

    Each host→device dispatch costs fixed latency (notably over remote
    TPU tunnels, where it is milliseconds); running the step under
    ``lax.scan`` amortises that cost over ``n_steps`` and lets XLA keep
    the whole loop resident on device — the TPU-native analogue of
    "steps_per_execution" loops.  The reference had no equivalent: its
    hot loop crossed the host every iteration by construction
    (``trainer.run()`` → ``optimizer.update`` per batch, SURVEY §3.1).

    Args:
      step_fn: ``step_fn(carry, *batch) -> (carry, metrics)`` — one
        training step in scan form.  ``carry`` is the full mutable train
        state pytree (params, opt state, model state, ...).
      n_steps: number of steps fused per call.
      scan_batches: if True, every ``batch`` leaf must have a leading
        axis of size ``n_steps`` and each step consumes one slice (the
        "pull K batches, stack, execute" loop); if False the same batch
        is re-used by every fused step (synthetic-data benchmarks).
      unroll: forwarded to ``lax.scan``.

    Returns ``fused(carry, *batch) -> (carry, metrics)`` where every
    ``metrics`` leaf gains a leading ``n_steps`` axis.  Wrap the result
    in ``jax.jit`` (donating the carry) before use.
    """
    from jax import lax

    def fused(carry, *batch):
        if scan_batches:
            return lax.scan(
                lambda c, b: step_fn(c, *b), carry, batch,
                length=n_steps, unroll=unroll)
        return lax.scan(
            lambda c, _: step_fn(c, *batch), carry, None,
            length=n_steps, unroll=unroll)

    return fused


def default_converter(batch):
    """List of tuples → tuple of stacked arrays (Chainer's concat_examples)."""
    if not batch:
        raise ValueError("empty batch")
    first = batch[0]
    if isinstance(first, (tuple, list)):
        cols = list(zip(*batch))
        return tuple(np.stack([np.asarray(v) for v in col]) for col in cols)
    return (np.stack([np.asarray(b) for b in batch]),)


class StandardUpdater:
    """Drives ``iterator → converter → jitted sharded step``.

    Args:
      iterator: yields local batches (list of examples).
      optimizer: optax transformation — normally the output of
        ``create_multi_node_optimizer`` so grads get pmean'd in-step.
      loss_fn: ``loss_fn(params, *batch_arrays) -> scalar`` local-shard loss;
        with ``state`` given, ``loss_fn(params, state, *batch_arrays) ->
        (scalar, new_state)`` instead (the Chainer "links hold mutable
        state" pattern — BN running stats — made explicit and threaded
        through the step).
      params: initial pytree (will be replicated via ``comm.bcast_data``).
      comm: communicator providing mesh + axis for batch sharding.
      state: optional non-trainable model state pytree.  Must come out of
        ``loss_fn`` cross-replica reduced (e.g. sync-BN ``pmean``'d
        statistics) so it stays replicated.
      steps_per_execution: fuse this many steps into one XLA call via
        :func:`fuse_steps` — ``update()`` pulls that many batches,
        stacks them, and runs the whole window on device, amortising
        per-dispatch latency.  ``iteration`` advances by the window
        size; ``main/loss`` reports the window mean.
    ZeRO-1 optimizers (``create_multi_node_optimizer(..., zero1=True)``)
    are detected from the transformation's type: their state is
    initialised per-shard via ``zero1_init`` and carried WORLD-STACKED
    (leading axis = mesh member) across steps, sharded over the data
    axis instead of replicated.
    """

    def __init__(
        self,
        iterator,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable,
        params,
        comm,
        converter: Callable = default_converter,
        drop_remainder: bool = True,
        state=None,
        steps_per_execution: int = 1,
    ):
        self.iterator = iterator
        self.optimizer = optimizer
        self.comm = comm
        self.converter = converter
        self.loss_fn = loss_fn
        self.drop_remainder = drop_remainder
        if steps_per_execution < 1:
            raise ValueError("steps_per_execution must be >= 1")
        self.steps_per_execution = steps_per_execution

        # first-update weight broadcast of the reference, done at init
        self.params = comm.bcast_data(params)
        self.state = None if state is None else comm.bcast_data(state)
        from .optimizers import Zero1Transformation, zero1_init

        self.zero1 = isinstance(optimizer, Zero1Transformation)
        if self.zero1:
            self.opt_state = zero1_init(
                optimizer, self.params, comm.mesh, comm.axis_name)
        else:
            self.opt_state = optimizer.init(self.params)

        self.iteration = 0
        self.epoch_detail = 0.0
        self.previous_epoch_detail = 0.0
        self.observation = {}

        self._step_cache = {}
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))
        # fused windows: leading n_steps axis is scanned, axis 1 sharded
        self._stacked_sharding = NamedSharding(
            comm.mesh, P(None, comm.axis_name))

    def _get_step(self, n_batch_args: int, n_steps: int = 1):
        """Jitted SPMD step, built per batch arity (x,) vs (x, y) vs ...
        and per fused window size ``n_steps`` (see ``steps_per_execution``;
        batch arrays then carry a leading ``n_steps`` axis)."""
        key = (n_batch_args, n_steps)
        if key in self._step_cache:
            return self._step_cache[key]
        ax = self.comm.axis_name
        optimizer, loss_fn = self.optimizer, self.loss_fn

        stateful = self.state is not None
        zero1 = self.zero1

        def step(carry, *batch):
            params, state, opt_state = carry
            if zero1:
                # world-stacked ZeRO state: this member's shard arrives
                # with a leading length-1 member axis — peel it for the
                # update, restack for the carry (zero1_init convention)
                opt_state = jax.tree.map(lambda s: s[0], opt_state)

            def global_loss(p):
                # pmean INSIDE the differentiated function: with replicated
                # params, shard_map's AD already psums cotangents across the
                # axis, so differentiating the pmean'd loss yields exactly
                # the global-mean gradient (no separate grad allreduce op —
                # this is where ChainerMN's multi_node_mean_grad went).
                if stateful:
                    loss, new_model_state = loss_fn(p, state, *batch)
                    return jax.lax.pmean(loss, ax), new_model_state
                return jax.lax.pmean(loss_fn(p, *batch), ax), state

            (loss, new_model_state), grads = jax.value_and_grad(
                global_loss, has_aux=True)(params)
            updates, new_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if zero1:
                new_state = jax.tree.map(lambda s: s[None], new_state)
            # loss is already the global mean (ObservationAggregator
            # semantics for the train loss come for free inside the step)
            return (new_params, new_model_state, new_state), loss

        fused = step if n_steps == 1 else fuse_steps(
            step, n_steps, scan_batches=True)
        # batch specs: the fused window's leading n_steps axis is a scan
        # axis, not a sharded one — only the per-example axis splits.
        # ZeRO-1 state is world-stacked: its leading member axis shards
        # over the data axis (each member holds its own 1/N slice).
        opt_spec = P(ax) if self.zero1 else P()
        fn = jax.jit(
            jax.shard_map(
                fused,
                mesh=self.comm.mesh,
                in_specs=((P(), P(), opt_spec),) + (P(*(
                    (None, ax) if n_steps > 1 else (ax,))),) * n_batch_args,
                out_specs=((P(), P(), opt_spec), P()),
            ),
            donate_argnums=(0,),
        )
        self._step_cache[key] = fn
        return fn

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    def _next_arrays(self):
        """Pull one batch, convert, apply the divisibility policy."""
        batch = next(self.iterator)
        arrays = self.converter(batch)
        n = self.comm.size
        if arrays[0].shape[0] % n:
            if not self.drop_remainder:
                raise ValueError(
                    f"global batch {arrays[0].shape[0]} not divisible by "
                    f"world size {n}")
            keep = (arrays[0].shape[0] // n) * n
            if keep == 0:
                raise ValueError(
                    f"batch of {arrays[0].shape[0]} examples cannot be "
                    f"sharded over {n} devices — raise batch_size to at "
                    f"least the world size")
            arrays = tuple(a[:keep] for a in arrays)
        return arrays

    def update(self):
        first = self._next_arrays()
        window = [first]
        pending = None
        # Fill the fused window; stop early on iterator exhaustion or a
        # ragged (end-of-epoch partial) batch, which can't stack — the
        # ragged batch then runs as its own single step below.
        while len(window) < self.steps_per_execution:
            try:
                nxt = self._next_arrays()
            except StopIteration:
                break
            if any(a.shape != b.shape for a, b in zip(nxt, first)):
                pending = nxt
                break
            window.append(nxt)

        k = len(window)
        if k == 1:
            arrays = tuple(
                jax.device_put(a, self._batch_sharding)
                for a in window[0])
        else:
            arrays = tuple(
                jax.device_put(
                    np.stack(cols), self._stacked_sharding)
                for cols in zip(*window))
        # step_time times the device step dispatch only (not the host-side
        # iterator pull / stacking), matching the unfused metric's meaning
        t0 = time.perf_counter()
        carry = (self.params, self.state, self.opt_state)
        carry, loss = self._get_step(len(arrays), k)(carry, *arrays)
        self.params, self.state, self.opt_state = carry
        step_time = time.perf_counter() - t0
        if pending is not None:
            # Ragged tail batch runs as a plain single step.  Its batch
            # shape differs from the steady-state one, so jit compiles
            # ONE extra executable the first time each distinct tail
            # shape appears (then cached) — a deliberate trade: padding
            # the tail instead would need a mask threaded through every
            # user loss_fn.  Only non-repeating epoch ends produce
            # ragged tails; steady training never pays this.
            arrays = tuple(
                jax.device_put(a, self._batch_sharding) for a in pending)
            t0 = time.perf_counter()
            carry = (self.params, self.state, self.opt_state)
            carry, tail_loss = self._get_step(len(arrays), 1)(
                carry, *arrays)
            self.params, self.state, self.opt_state = carry
            step_time += time.perf_counter() - t0
            loss = jnp.concatenate(
                [jnp.atleast_1d(loss), jnp.atleast_1d(tail_loss)])
            k += 1
        self.iteration += k
        self.previous_epoch_detail = self.epoch_detail
        self.epoch_detail = getattr(
            self.iterator, "epoch_detail", self.iteration)
        self.observation = {
            "main/loss": jnp.mean(loss) if k > 1 else loss,
            "main/step_time": step_time / k,
        }
