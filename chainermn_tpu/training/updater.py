"""StandardUpdater — the jitted data-parallel train step.

Replaces the reference's ``Updater → optimizer.update(lossfun) →
loss.backward() → comm.multi_node_mean_grad(model)`` hot loop (SURVEY §3.1)
with its TPU shape: ONE jitted SPMD program per step containing forward,
backward, cross-replica grad mean, and the optimiser update — so XLA can
fuse and overlap the collective with compute (what pure_nccl needed streams
and double-buffer threads for).

The global batch enters sharded over the communicator's mesh axis; params
stay replicated; optimiser state is replicated too, EXCEPT under ZeRO-1
(detected from the transformation type), where it is carried
world-stacked and sharded over the axis; the ``multi-node optimizer``'s
``cross_replica_mean`` supplies the ``pmean``.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.iterators.prefetch import (
    PrefetchIterator,
    StagingConverter,
    apply_batch_policy,
    assemble_window,
    default_converter,
    put_window,
)
from chainermn_tpu.utils.metrics import get_registry
from chainermn_tpu.utils.profiling import get_profiler
from chainermn_tpu.utils.programs import (
    get_accountant,
    get_ledger,
    ledger_jit,
    weakref_root,
)
from chainermn_tpu.utils.telemetry import get_recorder

__all__ = ["StandardUpdater", "default_converter", "fuse_steps"]


def fuse_steps(step_fn, n_steps: int, *, scan_batches: bool = False,
               unroll: int = 1):
    """Fuse ``n_steps`` training steps into ONE XLA program.

    Each host→device dispatch costs fixed latency (notably over remote
    TPU tunnels, where it is milliseconds); running the step under
    ``lax.scan`` amortises that cost over ``n_steps`` and lets XLA keep
    the whole loop resident on device — the TPU-native analogue of
    "steps_per_execution" loops.  The reference had no equivalent: its
    hot loop crossed the host every iteration by construction
    (``trainer.run()`` → ``optimizer.update`` per batch, SURVEY §3.1).

    Args:
      step_fn: ``step_fn(carry, *batch) -> (carry, metrics)`` — one
        training step in scan form.  ``carry`` is the full mutable train
        state pytree (params, opt state, model state, ...).
      n_steps: number of steps fused per call.
      scan_batches: if True, every ``batch`` leaf must have a leading
        axis of size ``n_steps`` and each step consumes one slice (the
        "pull K batches, stack, execute" loop); if False the same batch
        is re-used by every fused step (synthetic-data benchmarks).
      unroll: forwarded to ``lax.scan``.

    Returns ``fused(carry, *batch) -> (carry, metrics)`` where every
    ``metrics`` leaf gains a leading ``n_steps`` axis.  Wrap the result
    in ``jax.jit`` (donating the carry) before use.
    """
    from jax import lax

    def fused(carry, *batch):
        if scan_batches:
            return lax.scan(
                lambda c, b: step_fn(c, *b), carry, batch,
                length=n_steps, unroll=unroll)
        return lax.scan(
            lambda c, _: step_fn(c, *batch), carry, None,
            length=n_steps, unroll=unroll)

    return fused


class StandardUpdater:
    """Drives ``iterator → converter → jitted sharded step``.

    Args:
      iterator: yields local batches (list of examples).
      optimizer: optax transformation — normally the output of
        ``create_multi_node_optimizer`` so grads get pmean'd in-step.
      loss_fn: ``loss_fn(params, *batch_arrays) -> scalar`` local-shard loss;
        with ``state`` given, ``loss_fn(params, state, *batch_arrays) ->
        (scalar, new_state)`` instead (the Chainer "links hold mutable
        state" pattern — BN running stats — made explicit and threaded
        through the step).
      params: initial pytree (will be replicated via ``comm.bcast_data``).
      comm: communicator providing mesh + axis for batch sharding.
      state: optional non-trainable model state pytree.  Must come out of
        ``loss_fn`` cross-replica reduced (e.g. sync-BN ``pmean``'d
        statistics) so it stays replicated.
      steps_per_execution: fuse this many steps into one XLA call via
        :func:`fuse_steps` — ``update()`` pulls that many batches,
        stacks them, and runs the whole window on device, amortising
        per-dispatch latency.  ``iteration`` advances by the window
        size; ``main/loss`` reports the window mean.
      prefetch: overlap host assembly with device compute — wrap the
        iterator in a :class:`~chainermn_tpu.PrefetchIterator` of this
        slot depth (``True`` → depth 2), whose background worker pulls,
        converts, stacks AND ``device_put``s the next window while the
        current one computes.  ``self.iterator`` becomes the prefetcher
        (its ``state_dict`` drains in-flight slots, so checkpointing is
        unchanged).  0/False (default) keeps the serial feed.  See
        ``utils.comm_model.choose_prefetch_depth`` and
        ``docs/PIPELINE.md``.
      max_inflight: dispatched-but-unretired step-window cap.  Each
        ``update()`` dispatches without blocking, then retires the
        OLDEST outstanding window(s) until at most this many remain —
        donation recycles the carry buffers, so memory stays bounded
        while dispatch runs ahead of the device.  Defaults to 2 with
        ``prefetch`` (one computing + one dispatched behind it), else 1
        (each update waits for its predecessor — the natural async-
        dispatch overlap, now measured instead of destroyed).
      accum_steps: microbatched gradient accumulation with a
        window-fused exchange.  Each optimiser update consumes
        ``accum_steps`` microbatches inside ONE jitted donated-carry
        scan: every microbatch runs forward/backward on its *local*
        shard only (no per-microbatch cross-replica traffic — the mean
        moves OUT of the differentiated loss), local gradients
        accumulate in ``accum_dtype``, and the single window-end
        exchange happens inside the multi-node optimiser —
        ``cross_replica_mean``'s fused bucketed all-reduce (bf16 wire /
        hierarchical 2-stage exactly as configured there), or ZeRO-1's
        reduce-scatter/all-gather pair — so collective launches and
        wire bytes drop by ``accum_steps``× while the effective global
        batch grows by the same factor under fixed HBM.
        Correctness-equivalent to a single ``accum_steps``×-larger
        batch (equal-sized microbatches; mean of means).  The optimizer
        MUST be a multi-node one (``create_multi_node_optimizer``): in
        this mode its reducer is the ONLY gradient exchange, not a
        safety net.  ``iteration`` keeps counting microbatches (epoch
        arithmetic is the iterator's), so triggers fire on data
        consumed; parameters move once per ``accum_steps`` iterations.
        Composes multiplicatively with ``steps_per_execution``: one
        dispatch carries ``steps_per_execution × accum_steps``
        microbatches (``steps_per_execution`` optimiser updates).  A
        stateful ``loss_fn`` still updates (and, per its contract,
        cross-replica reduces) model state every microbatch.
        ``utils.comm_model.choose_accum_steps`` picks a principled M;
        ``utils.comm_model.assert_accum_collectives`` proves the M→1
        collective count from the compiled HLO.  See docs/PIPELINE.md.
        With a backward-overlapped optimizer
        (``create_multi_node_optimizer(overlap=...)``) the window-final
        microbatch is peeled out of the scan so the per-bucket exchange
        streams UNDER its backward pass
        (``assert_overlap_collectives`` is the proof; the peel reorders
        no accumulation arithmetic, and the overlap path composes
        bitwise with ``prefetch``/``steps_per_execution``).
      accum_dtype: gradient accumulator dtype (default float32 — wider
        than bf16 params so M summed microbatch grads don't lose
        mantissa).  The accumulated mean is cast back to each param
        leaf's dtype before the exchange, so the wire format is
        unchanged.
      exchange_probe_every: every this-many ``update()`` calls, re-time
        the optimizer's tuned exchange program in isolation (one extra
        exchange on a zeros grad tree, compiled once) and observe the
        wall time as ``main/exchange_time`` (profiler row
        ``updater/exchange_time``) — the window-end exchange cost the
        in-step fusion otherwise hides.  The observation also feeds the
        plan's drift guard (``plan_cell.observe``): when it departs
        from the plan's tuned time by the cell's ``drift_factor``,
        ``plan_cell.drifted`` flips and the owner may
        ``plan_cell.retune`` (see ``docs/TUNING.md``).  Requires a
        planned optimizer (``create_multi_node_optimizer(plan=...)``);
        0 (default) disables the probe.

    Timing observations (``utils.profiling`` names in parentheses):
    ``main/host_time`` (``updater/host_time``) is iterator pull +
    convert + stack + ``device_put`` — for a prefetched feed, the
    residual wait for the next ready window; ``main/device_time``
    (``updater/device_time``) is the exposed wait retiring windows past
    ``max_inflight``, i.e. blocking on the PREVIOUS window's result so
    steady-state timing stays overlapped; ``main/step_time`` is their
    per-iteration sum (the old value timed only the async dispatch
    call — it measured neither).

    ZeRO-1 optimizers (``create_multi_node_optimizer(..., zero1=True)``)
    are detected from the transformation's type: their state is
    initialised per-shard via ``zero1_init`` and carried WORLD-STACKED
    (leading axis = mesh member) across steps, sharded over the data
    axis instead of replicated.
    """

    def __init__(
        self,
        iterator,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable,
        params,
        comm,
        converter: Callable = default_converter,
        drop_remainder: bool = True,
        state=None,
        steps_per_execution: int = 1,
        prefetch: int = 0,
        max_inflight: Optional[int] = None,
        accum_steps: int = 1,
        accum_dtype=None,
        exchange_probe_every: int = 0,
    ):
        self.optimizer = optimizer
        self.comm = comm
        self.converter = converter
        self.loss_fn = loss_fn
        self.drop_remainder = drop_remainder
        if steps_per_execution < 1:
            raise ValueError("steps_per_execution must be >= 1")
        self.steps_per_execution = steps_per_execution
        if accum_steps < 1:
            raise ValueError("accum_steps must be >= 1")
        self.accum_steps = accum_steps
        self.accum_dtype = jnp.dtype(
            accum_dtype if accum_dtype is not None else jnp.float32)
        # one dispatch = steps_per_execution optimizer updates, each
        # consuming accum_steps microbatches: the window the feed
        # (serial or prefetched) assembles and stacks
        self.window_steps = steps_per_execution * accum_steps

        self.prefetch = 2 if prefetch is True else int(prefetch or 0)
        if self.prefetch < 0:
            raise ValueError("prefetch depth must be >= 0")
        if isinstance(iterator, PrefetchIterator) and not self.prefetch:
            # a pre-built prefetcher implies prefetch mode — adopting it
            # beats the opaque crash of feeding DeviceWindows to the
            # serial converter path
            self.prefetch = iterator.depth
        if isinstance(converter, StagingConverter) and \
                converter._n_buffers < self.window_steps + 1:
            raise ValueError(
                f"StagingConverter(n_buffers={converter._n_buffers}) "
                f"cannot hold a steps_per_execution × accum_steps = "
                f"{self.window_steps} window (needs >= window + 1 "
                f"buffers)")
        if max_inflight is None:
            max_inflight = 2 if self.prefetch else 1
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self._inflight: collections.deque = collections.deque()
        if self.prefetch:
            if isinstance(iterator, PrefetchIterator):
                # a pre-built prefetcher must agree with this updater's
                # window contract, or training silently runs a different
                # schedule than the constructor arguments claim
                if iterator._n_steps != self.window_steps:
                    raise ValueError(
                        f"PrefetchIterator was built with steps_per_"
                        f"execution={iterator._n_steps}, updater wants "
                        f"a {self.window_steps}-deep window "
                        f"(steps_per_execution × accum_steps)")
                if iterator._drop_remainder != drop_remainder:
                    raise ValueError(
                        "PrefetchIterator and updater disagree on "
                        "drop_remainder")
                self.prefetch = iterator.depth
                self.iterator = iterator
            else:
                self.iterator = PrefetchIterator(
                    iterator, comm,
                    # the default converter upgrades to a StagingConverter
                    # sized for the ring; an explicit converter is kept
                    converter=(None if converter is default_converter
                               else converter),
                    steps_per_execution=self.window_steps,
                    depth=self.prefetch,
                    drop_remainder=drop_remainder)
        else:
            self.iterator = iterator

        # first-update weight broadcast of the reference, done at init
        self.params = comm.bcast_data(params)
        self.state = None if state is None else comm.bcast_data(state)
        from .optimizers import (
            Zero1Transformation,
            Zero2Transformation,
            zero1_init,
        )

        # sharding mode from the transformation TYPE (never a repeated
        # flag): ZeRO-2 carries its state exactly like ZeRO-1 (world-
        # stacked 1/N shards — zero1_init and the P(ax) opt spec apply
        # verbatim), so self.zero1 stays the "world-stacked ZeRO carry"
        # switch for both
        self.sharding = (
            "zero2" if isinstance(optimizer, Zero2Transformation)
            else "zero1" if isinstance(optimizer, Zero1Transformation)
            else None)
        self.zero1 = self.sharding in ("zero1", "zero2")
        if self.zero1:
            self.opt_state = zero1_init(
                optimizer, self.params, comm.mesh, comm.axis_name)
        else:
            self.opt_state = optimizer.init(self.params)

        if exchange_probe_every < 0:
            raise ValueError("exchange_probe_every must be >= 0")
        if exchange_probe_every and \
                getattr(optimizer, "plan_cell", None) is None:
            raise ValueError(
                "exchange_probe_every needs a planned optimizer "
                "(create_multi_node_optimizer(plan=...)): the probe "
                "re-times the tuned exchange program, and the "
                "observation feeds its drift guard")
        self.exchange_probe_every = exchange_probe_every
        self._exchange_probe = None     # (plan, warmed fn, data factory)
        self._updates_done = 0
        # plan-cell generation this updater's compiled steps were built
        # against; update() compares and invalidates on change, so a
        # drift retune (or restored snapshot) can never leave training
        # silently running the old exchange program
        cell = getattr(optimizer, "plan_cell", None)
        self._plan_generation = None if cell is None else cell.generation

        self.iteration = 0
        self.epoch_detail = 0.0
        self.previous_epoch_detail = 0.0
        self.observation = {}
        self._last_retired = None

        self._step_cache = {}
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))
        # fused windows: leading n_steps axis is scanned, axis 1 sharded
        self._stacked_sharding = NamedSharding(
            comm.mesh, P(None, comm.axis_name))

    def _get_step(self, n_batch_args: int, n_steps: int = 1,
                  accum: int = 1):
        """Jitted SPMD step, built per batch arity (x,) vs (x, y) vs ...,
        per fused window size ``n_steps`` (see ``steps_per_execution``)
        and per accumulation depth ``accum`` (see ``accum_steps``; batch
        arrays then carry a leading ``n_steps * accum`` axis)."""
        key = (n_batch_args, n_steps, accum)
        if key in self._step_cache:
            return self._step_cache[key]
        ax = self.comm.axis_name
        optimizer, loss_fn = self.optimizer, self.loss_fn

        stateful = self.state is not None
        zero1 = self.zero1
        accum_dtype = self.accum_dtype
        # Backward-overlapped exchange (plan strategy "overlap", or a
        # zero1 transformation built with overlap=True): the window-
        # final microbatch is PEELED out of the accumulation scan.  A
        # scan is one opaque while op — every gradient leaf becomes
        # available only when the whole loop retires, so an exchange
        # after it cannot start under any backward.  With the last
        # microbatch unrolled in the outer program, each exchange
        # bucket depends only on its own (accumulated + final) leaves
        # and the scheduler streams the bucket collectives under the
        # final backward (assert_overlap_collectives proves it).  The
        # peel re-orders no float math — the same M microbatch grads
        # accumulate in the same order; only the exchange lowering
        # differs from the window-end path (wire tolerance documented
        # on cross_replica_mean).
        # The step cache key need not carry this flag: a plan change
        # bumps the cell generation and update() clears the cache.
        plan = getattr(getattr(optimizer, "plan_cell", None), "plan",
                       None)
        overlap_peel = accum > 1 and (
            getattr(plan, "strategy", None) == "overlap"
            or getattr(optimizer, "overlap", False))
        from chainermn_tpu.parallel._compat import pcast as _pcast

        def step(carry, *batch):
            params, state, opt_state = carry
            if zero1:
                # world-stacked ZeRO state: this member's shard arrives
                # with a leading length-1 member axis — peel it for the
                # update, restack for the carry (zero1_init convention)
                opt_state = jax.tree.map(lambda s: s[0], opt_state)

            if accum == 1:
                def global_loss(p):
                    # pmean INSIDE the differentiated function: the
                    # reported loss is the global mean, and on vma-typed
                    # jax shard_map's AD psums the cotangents of the
                    # replicated params so grads leave as the global
                    # mean too.  On pre-vma jax grads leave device-local
                    # instead — either way the multi-node optimizer's
                    # idempotent cross_replica_mean / ZeRO
                    # reduce-scatter settles the exchange (this is where
                    # ChainerMN's multi_node_mean_grad went).
                    if stateful:
                        loss, new_model_state = loss_fn(p, state, *batch)
                        return jax.lax.pmean(loss, ax), new_model_state
                    return jax.lax.pmean(loss_fn(p, *batch), ax), state

                (loss, new_model_state), grads = jax.value_and_grad(
                    global_loss, has_aux=True)(params)
            else:
                # Microbatch accumulation: a donated-carry scan of LOCAL
                # forward/backward passes — no collective of any kind
                # inside the loop body (assert_accum_collectives pins
                # this on the compiled HLO).  Differentiating the raw
                # local loss (the mean moved OUT of the differentiated
                # function) keeps cotangents device-local; on vma-typed
                # jax the pcast makes that explicit by differentiating
                # w.r.t. the varying retype of params (identity pre-vma).
                p_local = jax.tree.map(
                    lambda x: _pcast(x, ax, to="varying"), params)

                def micro(mcarry, mb):
                    acc, st = mcarry

                    def local_loss(p):
                        if stateful:
                            loss, new_st = loss_fn(p, st, *mb)
                            return loss, new_st
                        return loss_fn(p, *mb), st

                    (mloss, new_st), g = jax.value_and_grad(
                        local_loss, has_aux=True)(p_local)
                    # accumulate in accum_dtype (fp32 default): M summed
                    # bf16 microbatch grads would lose low-order bits
                    acc = jax.tree.map(
                        lambda a, gg: a + gg.astype(a.dtype), acc, g)
                    return (acc, new_st), mloss

                acc0 = jax.tree.map(
                    lambda p: _pcast(jnp.zeros(p.shape, accum_dtype),
                                     ax, to="varying"), params)
                if overlap_peel:
                    # scan the first M-1 microbatches, unroll the final
                    # one: its backward lands in the OUTER program,
                    # where the optimizer's per-bucket exchange can
                    # start while earlier layers' grads are still being
                    # produced (see the overlap_peel note above)
                    (acc, mid_state), micro_losses = jax.lax.scan(
                        micro, (acc0, state),
                        tuple(b[:-1] for b in batch))
                    (acc, new_model_state), last_loss = micro(
                        (acc, mid_state), tuple(b[-1] for b in batch))
                    micro_losses = jnp.concatenate(
                        [micro_losses, last_loss[None]])
                else:
                    (acc, new_model_state), micro_losses = jax.lax.scan(
                        micro, (acc0, state), batch)
                # local mean over the window, cast back to wire dtype;
                # STILL device-local — the optimizer's reducer performs
                # the single window-end cross-replica mean (fused
                # buckets / bf16 wire / hierarchical 2-stage for
                # cross_replica_mean, reduce-scatter for ZeRO-1)
                grads = jax.tree.map(
                    lambda a, p: (a / accum).astype(p.dtype), acc, params)
                # one scalar pmean per WINDOW for the reported loss (4
                # wire bytes — the `extra` assert_accum_collectives
                # allows); sits after the scan, never inside it
                loss = jax.lax.pmean(jnp.mean(micro_losses), ax)
            updates, new_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if zero1:
                new_state = jax.tree.map(lambda s: s[None], new_state)
            # loss is already the global mean (ObservationAggregator
            # semantics for the train loss come for free inside the step)
            return (new_params, new_model_state, new_state), loss

        fused = step if n_steps == 1 else fuse_steps(
            step, n_steps, scan_batches=True)
        if accum > 1 and n_steps > 1:
            # the feed stacks a flat (n_steps * accum)-deep window; the
            # outer fused-step scan consumes one accum-deep microbatch
            # block per optimiser update
            inner = fused

            def fused(carry, *batch):  # noqa: F811 — deliberate re-wrap
                return inner(carry, *(
                    b.reshape((n_steps, accum) + b.shape[1:])
                    for b in batch))

        window = n_steps * accum
        # batch specs: the window's leading scan axis is a scan axis,
        # not a sharded one — only the per-example axis splits.
        # ZeRO-1 state is world-stacked: its leading member axis shards
        # over the data axis (each member holds its own 1/N slice).
        opt_spec = P(ax) if self.zero1 else P()
        # the program ledger's cache-miss hook rides every step
        # program: the steady window, the accum-group/single-step tail
        # programs, and each distinct ragged tail shape record their
        # compiles (and signature diffs) under ONE label — exactly the
        # per-shape attribution the epoch-tail recompile story needs
        fn = ledger_jit(
            jax.shard_map(
                fused,
                mesh=self.comm.mesh,
                in_specs=((P(), P(), opt_spec),) + (P(*(
                    (None, ax) if window > 1 else (ax,))),) * n_batch_args,
                out_specs=((P(), P(), opt_spec), P()),
            ),
            label="train/step",
            donate_argnums=(0,),
        )
        self._step_cache[key] = fn
        return fn

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    def status(self) -> dict:
        """The training-progress block for a ``/statusz`` surface
        (``StatuszServer.add_section("train", updater)``): where the
        loop is — iteration/epoch, the world it runs over, and how
        much work is in flight — read-only and cheap enough to serve
        per scrape."""
        return {
            "iteration": int(self.iteration),
            "epoch": int(self.epoch),
            "world_size": int(getattr(self.comm, "inter_size", 1)),
            "steps_per_execution": int(self.steps_per_execution),
            "inflight_windows": len(self._inflight),
            "zero1": bool(self.zero1),
            "sharding": self.sharding,
        }

    def mark_steady(self) -> None:
        """Declare the training step programs steady-state in the
        program ledger (call after step 1 has compiled the steady
        window): any further ``train/`` compile — a shape leak in the
        feed, a plan-change recompile outside a declared retune —
        counts as ``compile/steady_retraces`` and feeds the
        retrace-storm alert.  Epoch tails are part of steady training
        only if their shapes repeat; the first epoch's tail compiles
        BEFORE marking if tails are expected (run one full epoch
        first, or accept the one attributed event)."""
        get_ledger().mark_steady("train/")

    def register_memory(self, accountant=None,
                        prefix: str = "train") -> None:
        """Register the training state's device-buffer roots with the
        memory accountant: ``<prefix>_params``, ``<prefix>_opt_state``
        (the full or ZeRO-sharded optimizer state), ``<prefix>_state``
        (model state, when carried).  Weakref-held
        (``programs.weakref_root``) — registration never pins a
        retired updater; dead roots sample as 0."""
        acc = accountant if accountant is not None else get_accountant()
        acc.register(f"{prefix}_params", weakref_root(self, "params"))
        acc.register(f"{prefix}_opt_state",
                     weakref_root(self, "opt_state"))
        if self.state is not None:
            acc.register(f"{prefix}_state", weakref_root(self, "state"))

    def rebind_world(self, comm, optimizer) -> None:
        """Re-bind this updater to a NEW communicator/mesh mid-run — the
        live-resize half of ``training/elastic.py`` (the
        ``ResizeController`` calls this at the paused step boundary,
        after re-laying the train state for the new world).

        Everything derived from the old mesh is rebuilt or dropped: the
        compiled step cache (its programs baked the old mesh), the batch
        shardings, the exchange-probe program, and the plan-generation
        watermark (the fresh optimizer re-tunes for the new topology).
        A prefetching feed is closed — returning its unconsumed
        lookahead to the base iterator — and re-wrapped over the new
        communicator, so the data position is exactly where a
        save/restart at this boundary would resume.  The caller owes:
        draining in-flight windows FIRST (the old mesh's buffers must
        retire before the world changes) and installing the re-laid
        ``params`` / ``opt_state`` / ``state`` afterwards."""
        from .optimizers import Zero1Transformation, Zero2Transformation

        if isinstance(self.iterator, PrefetchIterator):
            base = self.iterator._base
            depth = self.iterator.depth
            # the prefetcher's RESOLVED converter, not the updater's: a
            # pre-built feed may carry its own (e.g. a custom
            # StagingConverter) while self.converter sits at the
            # default — rebuilding with the wrong one would convert
            # post-resize batches differently and break trajectory
            # equivalence.  Reuse is safe: in-flight windows are
            # drained by the caller and close() joins the worker.
            conv = self.iterator._converter
            self.iterator.close()
            self.iterator = PrefetchIterator(
                base, comm,
                converter=conv,
                steps_per_execution=self.window_steps,
                depth=depth,
                drop_remainder=self.drop_remainder)
        self.comm = comm
        self.optimizer = optimizer
        was_sharding = self.sharding
        self.sharding = (
            "zero2" if isinstance(optimizer, Zero2Transformation)
            else "zero1" if isinstance(optimizer, Zero1Transformation)
            else None)
        self.zero1 = self.sharding in ("zero1", "zero2")
        if self.sharding != was_sharding:
            raise ValueError(
                f"rebind_world cannot switch sharding mode mid-run "
                f"({was_sharding!r} -> {self.sharding!r}): the carried "
                f"optimizer state's layout would not match the new "
                f"transformation")
        cell = getattr(optimizer, "plan_cell", None)
        if self.exchange_probe_every and cell is None:
            raise ValueError(
                "rebind_world: exchange_probe_every is set but the new "
                "optimizer is not a planned one "
                "(create_multi_node_optimizer(plan=...))")
        self._plan_generation = None if cell is None else cell.generation
        self._exchange_probe = None
        self._step_cache = {}
        # the rebuilt step programs are NEW executables: drop the
        # program ledger's train/ signature memory (and any steady
        # declaration) so the post-resize recompile is re-recorded —
        # even when the new world returns to a previously-seen shape
        get_ledger().forget("train/")
        self._inflight.clear()
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))
        self._stacked_sharding = NamedSharding(
            comm.mesh, P(None, comm.axis_name))

    def finalize(self):
        """Release the feed: joins a prefetching iterator's worker and
        returns its unconsumed lookahead to the base iterator.  The
        trainer calls this when ``run()`` exits; safe to call more than
        once, and the feed restarts transparently if training resumes.
        Only the updater-owned prefetch wrap is closed — a user-supplied
        iterator's own ``close`` (a file handle, a stream) is not the
        updater's to call."""
        if isinstance(self.iterator, PrefetchIterator):
            self.iterator.close()

    def _next_arrays(self):
        """Pull one batch, convert, apply the divisibility policy."""
        arrays = self.converter(next(self.iterator))
        return apply_batch_policy(arrays, self.comm.size,
                                  self.drop_remainder)

    def _assemble_host_window(self):
        """The serial feed: pull, convert, stack and ``device_put`` the
        next fused window on the calling thread, via the SAME
        ``assemble_window``/``put_window`` helpers the prefetch worker
        runs — one window contract, so the prefetch-on/off bitwise
        parity cannot drift.  Returns ``(arrays, k, tail)`` in exactly
        the layout :class:`PrefetchIterator` delivers ready-made."""
        window, pending = assemble_window(
            self._next_arrays, self.window_steps)
        return put_window(window, pending, self._batch_sharding,
                          self._stacked_sharding, converter=self.converter,
                          source=self.iterator)

    def _dispatch_window(self, carry, arrays, k):
        """Run a ``k``-microbatch window through CACHED programs only.

        The steady window (``k == window_steps``) runs the one fused/
        accumulating executable.  A shorter tail-of-epoch window is
        FLUSHED through the ``n_steps=1`` programs instead — full
        ``accum_steps`` groups through the single-update accumulating
        program, leftovers as plain single steps — so a partial window
        never compiles a one-off ``(k, ...)`` shape (the first epoch
        end used to pay a fresh steady-state-sized XLA compile for a
        shape that recurs at most once per epoch).  Returns
        ``(carry, losses, weights, n_updates)`` — ``weights`` holds the
        microbatch count behind each loss element, so the observed
        window loss can stay an unbiased per-microbatch mean when
        M-deep window means mix with single-step losses.
        """
        M, n_args = self.accum_steps, len(arrays)
        if k == self.window_steps and k > 1:
            carry, loss = self._get_step(
                n_args, self.steps_per_execution, M)(carry, *arrays)
            return (carry, [jnp.atleast_1d(loss)],
                    [M] * self.steps_per_execution,
                    self.steps_per_execution)
        if k == 1:
            # put_window delivers a lone microbatch unstacked
            carry, loss = self._get_step(n_args, 1, 1)(carry, *arrays)
            return carry, [jnp.atleast_1d(loss)], [1], 1
        losses, weights, n_updates = [], [], 0
        q = k // M if M > 1 else 0
        for i in range(q):
            seg = tuple(a[i * M:(i + 1) * M] for a in arrays)
            carry, loss = self._get_step(n_args, 1, M)(carry, *seg)
            losses.append(jnp.atleast_1d(loss))
            weights.append(M)
            n_updates += 1
        for j in range(q * M, k):
            # leftover microbatches (including the whole window when
            # accum is off) run as plain single steps: each is a full
            # optimizer update, exactly what an unfused updater would do
            seg = tuple(a[j] for a in arrays)
            carry, loss = self._get_step(n_args, 1, 1)(carry, *seg)
            losses.append(jnp.atleast_1d(loss))
            weights.append(1)
            n_updates += 1
        return carry, losses, weights, n_updates

    def _probe_exchange_time(self) -> float:
        """Time one isolated execution of the tuned exchange program on
        a zeros grad tree — the ``main/exchange_time`` observation.
        The program is built (and warmed) once per plan; a plan change
        (drift re-tune, snapshot restore) rebuilds it."""
        from chainermn_tpu.utils import autotune as _autotune

        cell = self.optimizer.plan_cell
        plan = cell.plan
        if plan is None:
            raise RuntimeError(
                "exchange probe with an unresolved plan — init ran?")
        if self._exchange_probe is None \
                or self._exchange_probe[0] is not plan:
            fn, make_data = _autotune.build_plan_probe(
                self.comm, plan, self.params)
            self._exchange_probe = (plan, fn, make_data)
        _, fn, make_data = self._exchange_probe
        # the probe tree is rebuilt per probe (and dropped after), so
        # no gradient-tree-sized buffer stays pinned between probes
        data = make_data()
        # drain in-flight training windows BEFORE the timer starts: the
        # probe must measure the exchange in isolation, not the queued
        # windows it would otherwise sit behind (a spuriously inflated
        # observation would trip the drift guard every probe).  Blocks
        # without popping, so the retire bookkeeping is untouched.
        for pending in self._inflight:
            jax.block_until_ready(pending)
        t0 = time.perf_counter()
        jax.block_until_ready(fn(data))
        dt = time.perf_counter() - t0
        cell.observe(dt)
        return dt

    def update(self):
        # -- plan-change barrier: recompile steps that baked in a now-
        # replaced exchange plan (drift retune / snapshot restore) ---- #
        cell = getattr(self.optimizer, "plan_cell", None)
        if cell is not None and cell.generation != self._plan_generation:
            self._step_cache.clear()
            self._plan_generation = cell.generation
            get_recorder().instant("step/plan_change", cat="step",
                                   step=self.iteration,
                                   generation=cell.generation)
        tracer = get_recorder()

        # -- host phase: obtain the next device-resident window -------- #
        t0 = time.perf_counter()
        with tracer.span("step/host", cat="step", step=self.iteration,
                         prefetch=bool(self.prefetch)):
            if self.prefetch:
                rec = next(self.iterator)   # DeviceWindow, pre-transferred
                arrays, k, tail = rec.arrays, rec.k, rec.tail
            else:
                arrays, k, tail = self._assemble_host_window()
        host_time = time.perf_counter() - t0

        # -- dispatch (non-blocking under JAX async dispatch) ----------- #
        # the accumulation window IS the dispatch when accum is on — the
        # span name keeps the two regimes distinguishable in the trace
        dispatch_span = ("step/accum_window" if self.accum_steps > 1
                         else "step/dispatch")
        carry = (self.params, self.state, self.opt_state)
        with tracer.span(dispatch_span, cat="step", step=self.iteration,
                         k=k, accum_steps=self.accum_steps):
            carry, losses, weights, n_updates = self._dispatch_window(
                carry, arrays, k)
        n_iters = k
        if tail is not None:
            # Ragged tail batch runs as a plain single step.  Its batch
            # shape differs from the steady-state one, so jit compiles
            # ONE extra executable the first time each distinct tail
            # shape appears (then cached) — a deliberate trade: padding
            # the tail instead would need a mask threaded through every
            # user loss_fn.  Only non-repeating epoch ends produce
            # ragged tails; steady training never pays this.
            carry, tail_loss = self._get_step(len(tail), 1)(carry, *tail)
            losses.append(jnp.atleast_1d(tail_loss))
            weights.append(1)
            n_iters += 1
            n_updates += 1
        loss = losses[0] if len(losses) == 1 else jnp.concatenate(losses)
        if loss.size == 1 or len(set(weights)) == 1:
            # equal weights (the steady state): plain mean is unbiased
            window_loss = jnp.mean(loss)
        else:
            # mixed M-deep window means and single-step losses (epoch
            # tails under accumulation): weight each element by the
            # microbatches behind it so the reported loss stays the
            # per-microbatch mean the unfused path would log
            w = jnp.asarray(weights, loss.dtype)
            window_loss = jnp.dot(loss, w) / w.sum()
        self.params, self.state, self.opt_state = carry

        # -- retire: block on the oldest window(s) past max_inflight ---- #
        # (the PREVIOUS window in steady state — never the one just
        # dispatched — so the measured device wait is the exposed cost,
        # not the full step latency, and the pipeline stays overlapped;
        # donated carries bound memory to max_inflight windows)
        # the weighted window loss derives from every dispatched
        # program's output, so blocking on it retires the whole window
        self._inflight.append(window_loss)
        t0 = time.perf_counter()
        with tracer.span("step/retire", cat="step", step=self.iteration,
                         inflight=len(self._inflight)):
            while len(self._inflight) > self.max_inflight:
                retired = self._inflight.popleft()
                jax.block_until_ready(retired)
                self._last_retired = retired
        device_time = time.perf_counter() - t0

        self.iteration += n_iters
        self.previous_epoch_detail = self.epoch_detail
        self.epoch_detail = getattr(
            self.iterator, "epoch_detail", self.iteration)
        prof = get_profiler()
        prof.record("updater/host_time", host_time)
        prof.record("updater/device_time", device_time)
        if self.max_inflight > 1 and self._last_retired is not None:
            # pipelined: report the RETIRED window's loss (already
            # materialised) so a float()-per-iteration consumer —
            # LogReport.observe, PrintReport — never stalls the
            # pipeline on the in-flight window.  Lags by max_inflight
            # updates; the serial path keeps the current (async) loss.
            obs_loss = self._last_retired
        else:
            obs_loss = window_loss
        self.observation = {
            "main/loss": obs_loss,
            "main/host_time": host_time / n_iters,
            "main/device_time": device_time / n_iters,
            "main/step_time": (host_time + device_time) / n_iters,
        }
        # the step-time DISTRIBUTION (not just this tick's value): the
        # metrics registry's lattice histogram feeds p50/p99 step-time
        # SLOs and the Prometheus exposition; no-op while disabled
        reg = get_registry()
        reg.observe("train/step_time", (host_time + device_time) / n_iters)
        reg.inc("train/iterations", n_iters)
        if self.accum_steps > 1:
            # wall time per OPTIMIZER update (the window), vs step_time's
            # per-microbatch denominator — the pair makes the
            # amortisation visible (accum_time ≈ accum_steps × step_time
            # means the exchange really left the microbatch loop)
            accum_time = (host_time + device_time) / max(n_updates, 1)
            prof.record("updater/accum_time", accum_time)
            self.observation["main/accum_time"] = accum_time
        self._updates_done += 1
        if self.exchange_probe_every and \
                self._updates_done % self.exchange_probe_every == 0:
            # span covers drain + isolated run; the isolated measurement
            # itself rides the metadata
            with tracer.span("step/exchange_probe", cat="step",
                             step=self.iteration) as probe_span:
                exchange_time = self._probe_exchange_time()
                probe_span.set(exchange_s=round(exchange_time, 6))
            prof.record("updater/exchange_time", exchange_time)
            self.observation["main/exchange_time"] = exchange_time
