"""StandardUpdater — the jitted data-parallel train step.

Replaces the reference's ``Updater → optimizer.update(lossfun) →
loss.backward() → comm.multi_node_mean_grad(model)`` hot loop (SURVEY §3.1)
with its TPU shape: ONE jitted SPMD program per step containing forward,
backward, cross-replica grad mean, and the optimiser update — so XLA can
fuse and overlap the collective with compute (what pure_nccl needed streams
and double-buffer threads for).

The global batch enters sharded over the communicator's mesh axis; params
and optimiser state stay replicated; the ``multi-node optimizer``'s
``cross_replica_mean`` supplies the ``pmean``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["StandardUpdater", "default_converter"]


def default_converter(batch):
    """List of tuples → tuple of stacked arrays (Chainer's concat_examples)."""
    if not batch:
        raise ValueError("empty batch")
    first = batch[0]
    if isinstance(first, (tuple, list)):
        cols = list(zip(*batch))
        return tuple(np.stack([np.asarray(v) for v in col]) for col in cols)
    return (np.stack([np.asarray(b) for b in batch]),)


class StandardUpdater:
    """Drives ``iterator → converter → jitted sharded step``.

    Args:
      iterator: yields local batches (list of examples).
      optimizer: optax transformation — normally the output of
        ``create_multi_node_optimizer`` so grads get pmean'd in-step.
      loss_fn: ``loss_fn(params, *batch_arrays) -> scalar`` local-shard loss;
        with ``state`` given, ``loss_fn(params, state, *batch_arrays) ->
        (scalar, new_state)`` instead (the Chainer "links hold mutable
        state" pattern — BN running stats — made explicit and threaded
        through the step).
      params: initial pytree (will be replicated via ``comm.bcast_data``).
      comm: communicator providing mesh + axis for batch sharding.
      state: optional non-trainable model state pytree.  Must come out of
        ``loss_fn`` cross-replica reduced (e.g. sync-BN ``pmean``'d
        statistics) so it stays replicated.
    """

    def __init__(
        self,
        iterator,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable,
        params,
        comm,
        converter: Callable = default_converter,
        drop_remainder: bool = True,
        state=None,
    ):
        self.iterator = iterator
        self.optimizer = optimizer
        self.comm = comm
        self.converter = converter
        self.loss_fn = loss_fn
        self.drop_remainder = drop_remainder

        # first-update weight broadcast of the reference, done at init
        self.params = comm.bcast_data(params)
        self.state = None if state is None else comm.bcast_data(state)
        self.opt_state = optimizer.init(self.params)

        self.iteration = 0
        self.epoch_detail = 0.0
        self.previous_epoch_detail = 0.0
        self.observation = {}

        self._step_cache = {}
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))

    def _get_step(self, n_batch_args: int):
        """Jitted SPMD step, built per batch arity (x,) vs (x, y) vs ..."""
        if n_batch_args in self._step_cache:
            return self._step_cache[n_batch_args]
        ax = self.comm.axis_name
        optimizer, loss_fn = self.optimizer, self.loss_fn

        stateful = self.state is not None

        def step(params, state, opt_state, *batch):
            def global_loss(p):
                # pmean INSIDE the differentiated function: with replicated
                # params, shard_map's AD already psums cotangents across the
                # axis, so differentiating the pmean'd loss yields exactly
                # the global-mean gradient (no separate grad allreduce op —
                # this is where ChainerMN's multi_node_mean_grad went).
                if stateful:
                    loss, new_model_state = loss_fn(p, state, *batch)
                    return jax.lax.pmean(loss, ax), new_model_state
                return jax.lax.pmean(loss_fn(p, *batch), ax), state

            (loss, new_model_state), grads = jax.value_and_grad(
                global_loss, has_aux=True)(params)
            updates, new_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # loss is already the global mean (ObservationAggregator
            # semantics for the train loss come for free inside the step)
            return new_params, new_model_state, new_state, loss

        fn = jax.jit(
            jax.shard_map(
                step,
                mesh=self.comm.mesh,
                in_specs=(P(), P(), P()) + (P(ax),) * n_batch_args,
                out_specs=(P(), P(), P(), P()),
            ),
            donate_argnums=(0, 1, 2),
        )
        self._step_cache[n_batch_args] = fn
        return fn

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    def update(self):
        batch = next(self.iterator)
        arrays = self.converter(batch)
        n = self.comm.size
        if arrays[0].shape[0] % n:
            if not self.drop_remainder:
                raise ValueError(
                    f"global batch {arrays[0].shape[0]} not divisible by "
                    f"world size {n}")
            keep = (arrays[0].shape[0] // n) * n
            if keep == 0:
                raise ValueError(
                    f"batch of {arrays[0].shape[0]} examples cannot be "
                    f"sharded over {n} devices — raise batch_size to at "
                    f"least the world size")
            arrays = tuple(a[:keep] for a in arrays)
        arrays = tuple(
            jax.device_put(a, self._batch_sharding) for a in arrays)
        t0 = time.perf_counter()
        self.params, self.state, self.opt_state, loss = \
            self._get_step(len(arrays))(
                self.params, self.state, self.opt_state, *arrays)
        self.iteration += 1
        self.previous_epoch_detail = self.epoch_detail
        self.epoch_detail = getattr(
            self.iterator, "epoch_detail", self.iteration)
        self.observation = {
            "main/loss": loss,
            "main/step_time": time.perf_counter() - t0,
        }
