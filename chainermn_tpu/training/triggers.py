"""Interval triggers for the trainer loop (Chainer-protocol analogue: the
reference's extensions fire on ``(period, 'epoch'|'iteration')`` tuples)."""

from __future__ import annotations


class IntervalTrigger:
    def __init__(self, period: float, unit: str):
        if unit not in ("epoch", "iteration"):
            raise ValueError(f"unit must be epoch|iteration, got {unit!r}")
        self.period = period
        self.unit = unit
        self._last_fired_count = -1

    def __call__(self, trainer) -> bool:
        if self.unit == "iteration":
            it = trainer.updater.iteration
            fire = it > 0 and it % self.period == 0
            return fire
        # epoch unit: fire when an epoch boundary was crossed this iteration
        prev = trainer.updater.previous_epoch_detail
        cur = trainer.updater.epoch_detail
        return int(cur / self.period) > int(prev / self.period)

    def __repr__(self):  # pragma: no cover
        return f"IntervalTrigger({self.period}, {self.unit!r})"


def get_trigger(trigger):
    if trigger is None:
        return lambda trainer: False
    if callable(trigger):
        return trigger
    period, unit = trigger
    return IntervalTrigger(period, unit)
