"""Interval triggers for the trainer loop (Chainer-protocol analogue: the
reference's extensions fire on ``(period, 'epoch'|'iteration')`` tuples)."""

from __future__ import annotations


class IntervalTrigger:
    def __init__(self, period: float, unit: str):
        if unit not in ("epoch", "iteration"):
            raise ValueError(f"unit must be epoch|iteration, got {unit!r}")
        self.period = period
        self.unit = unit
        # iteration unit uses CROSSING semantics (like the epoch branch):
        # with fused update windows (steps_per_execution > 1) iteration
        # advances by k per update, so ``it % period == 0`` would skip any
        # trigger point falling inside a window.
        self._seen_iteration = None
        self._seen_fire = False

    def initialize(self, trainer) -> None:
        """Called by ``Trainer.run`` before the loop: seed the crossing
        state from the CURRENT iteration, so a resumed run (iteration
        restored to e.g. 100 by ``maybe_load``) does not see a phantom
        0→101 crossing and fire every iteration-unit trigger once
        immediately after resume."""
        if self._seen_iteration is None and self.unit == "iteration":
            self._seen_iteration = trainer.updater.iteration

    def __call__(self, trainer) -> bool:
        if self.unit == "iteration":
            it = trainer.updater.iteration
            if it == self._seen_iteration:
                # idempotent within one iteration (an extension entry may
                # probe its trigger more than once per loop turn)
                return self._seen_fire
            prev = self._seen_iteration or 0
            self._seen_iteration = it
            self._seen_fire = it > 0 and \
                int(it / self.period) > int(prev / self.period)
            return self._seen_fire
        # epoch unit: fire when an epoch boundary was crossed this iteration
        prev = trainer.updater.previous_epoch_detail
        cur = trainer.updater.epoch_detail
        return int(cur / self.period) > int(prev / self.period)

    def __repr__(self):  # pragma: no cover
        return f"IntervalTrigger({self.period}, {self.unit!r})"


def get_trigger(trigger):
    if trigger is None:
        return lambda trainer: False
    if callable(trigger):
        return trigger
    period, unit = trigger
    return IntervalTrigger(period, unit)
