"""Multi-node evaluation — analogue of ``chainermn.create_multi_node_evaluator``
and ``GenericMultiNodeEvaluator`` (reference: ``chainermn/evaluators.py``,
``chainermn/extensions/generic_multi_node_evaluator.py``; unverified —
mount empty, see SURVEY.md).

Each process evaluates its scattered validation shard locally; the
observation dict is then averaged across processes with ``allreduce_obj`` so
reported metrics are global — exactly the reference's contract, with the
device-level averaging happening inside the jitted eval step (pmean) and the
process-level averaging on the host.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from chainermn_tpu.utils.programs import ledger_jit
from jax.sharding import NamedSharding, PartitionSpec as P

from .updater import default_converter

__all__ = ["Evaluator", "create_multi_node_evaluator",
           "GenericMultiNodeEvaluator"]


class Evaluator:
    """Runs ``metrics_fn(params, *batch) -> dict`` over a non-repeating
    iterator and averages per-batch metric dicts (weighted by batch
    size).

    Contract: each metric scalar must be the unweighted MEAN over the
    batch rows.  Both the cross-batch weighting here and the padded
    remainder step's real-row recovery (``_get_remainder_step``) are
    exact only under that linearity; a metric that weights rows
    internally (e.g. token-count-normalised loss over ragged rows)
    needs its numerator and denominator reported as separate mean
    metrics and combined after ``evaluate``."""

    trigger = (1, "epoch")
    priority = 80
    name = "validation"

    def __init__(self, iterator, metrics_fn: Callable, comm,
                 converter: Callable = default_converter,
                 get_params: Optional[Callable] = None):
        self.iterator = iterator
        self.comm = comm
        self.converter = converter
        self._get_params = get_params
        self._metrics_fn = metrics_fn
        self._step_cache = {}
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))
        # remainder rows (b mod world) never exceed world - 1: one fixed
        # bucket shape covers every possible tail
        self._rem_bucket = max(comm.size - 1, 1)

    def _get_eval_step(self, n_batch_args: int):
        if n_batch_args in self._step_cache:
            return self._step_cache[n_batch_args]
        ax = self.comm.axis_name
        metrics_fn = self._metrics_fn

        def shard_metrics(params, *batch):
            m = metrics_fn(params, *batch)
            return {k: jax.lax.pmean(v, ax) for k, v in m.items()}

        fn = ledger_jit(
            jax.shard_map(
                shard_metrics, mesh=self.comm.mesh,
                in_specs=(P(),) + (P(ax),) * n_batch_args, out_specs=P(),
            ),
            label="eval/metrics",
        )
        self._step_cache[n_batch_args] = fn
        return fn

    def _get_remainder_step(self, n_batch_args: int):
        """Unsharded eval step for batch rows that don't divide the world
        size — evaluated replicated so that every validation example
        contributes (the reference evaluated all examples; dropping the
        remainder would make metrics a function of batch divisibility).

        The tail arrives PADDED to the fixed ``world - 1`` bucket (pad
        rows are copies of row 0), so every possible remainder length
        shares ONE executable — the bare ``jit(metrics_fn)`` it replaces
        retraced for each distinct tail length, a fresh XLA compile per
        epoch-end shape (evaluation now compiles at most twice per batch
        arity: the sharded main step plus this bucket).  The real-row
        weighting recovers the unpadded means exactly: ``metrics_fn``
        returns batch-MEAN scalars (the contract ``evaluate`` already
        leans on when it weights per-batch dicts by batch size), so with
        ``r`` real rows in a bucket of ``T``,

            ``m_real = (T·m_padded − (T−r)·m_row0) / r``

        where ``m_row0`` — the metrics of a bucket filled with row 0,
        exactly the padding's contribution — comes from a second call of
        the SAME shape inside the jitted step (no extra executable).
        """
        key = ("rem", n_batch_args)
        if key in self._step_cache:
            return self._step_cache[key]
        metrics_fn = self._metrics_fn

        def padded_metrics(params, n_real, *batch):
            total = batch[0].shape[0]
            m_pad = metrics_fn(params, *batch)
            row0 = tuple(jnp.broadcast_to(a[:1], a.shape) for a in batch)
            m_row0 = metrics_fn(params, *row0)
            n_fill = total - n_real
            return {k: (total * m_pad[k] - n_fill * m_row0[k]) / n_real
                    for k in m_pad}

        fn = ledger_jit(padded_metrics, label="eval/remainder")
        self._step_cache[key] = fn
        return fn

    def _pad_remainder(self, rem):
        """Pad tail columns to the fixed bucket with copies of row 0."""
        bucket = self._rem_bucket
        r = rem[0].shape[0]
        if r == bucket:
            return rem
        return tuple(
            np.concatenate(
                [np.asarray(a),
                 np.broadcast_to(np.asarray(a[:1]),
                                 (bucket - r,) + tuple(a.shape[1:]))])
            for a in rem)

    def evaluate(self, params) -> Dict[str, float]:
        if getattr(self.iterator, "repeat", False):
            raise ValueError(
                "evaluation iterator must not repeat (pass repeat=False) — "
                "a repeating iterator never exhausts and would hang the "
                "epoch trigger")
        self.iterator.reset()
        totals, weight = {}, 0
        n = self.comm.size
        for batch in self.iterator:
            arrays = self.converter(batch)
            b = arrays[0].shape[0]
            keep = (b // n) * n
            if keep:
                main = tuple(
                    jax.device_put(a[:keep], self._batch_sharding)
                    for a in arrays)
                m = self._get_eval_step(len(main))(params, *main)
                for k, v in m.items():
                    totals[k] = totals.get(k, 0.0) + float(v) * keep
                weight += keep
            if keep < b:
                rem = self._pad_remainder(tuple(a[keep:] for a in arrays))
                m = self._get_remainder_step(len(rem))(
                    params, np.float32(b - keep), *rem)
                for k, v in m.items():
                    totals[k] = totals.get(k, 0.0) + float(v) * (b - keep)
                weight += b - keep
        local = {k: v / max(weight, 1) for k, v in totals.items()}
        return local

    def _resolve_params(self, trainer):
        """What to evaluate — the ``get_params`` hook or the live params
        (shared by the multi-node wrapper so the logic can't drift)."""
        return (self._get_params(trainer) if self._get_params
                else trainer.updater.params)

    def __call__(self, trainer):
        obs = self.evaluate(self._resolve_params(trainer))
        trainer.observation.update(
            {f"{self.name}/{k}": v for k, v in obs.items()})
        return obs


class _MultiNodeEvaluator:
    """Wraps any evaluator-like object: local evaluate, then allreduce-mean
    the observation dict across processes."""

    def __init__(self, evaluator, comm):
        self._evaluator = evaluator
        self._comm = comm
        for attr in ("trigger", "priority", "name", "iterator"):
            if hasattr(evaluator, attr):
                setattr(self, attr, getattr(evaluator, attr))

    def evaluate(self, params):
        local = self._evaluator.evaluate(params)
        return self._comm.allreduce_obj(local, op="mean")

    def __call__(self, trainer):
        resolve = getattr(self._evaluator, "_resolve_params", None)
        params = (resolve(trainer) if resolve
                  else getattr(trainer.updater, "params", None))
        obs = self.evaluate(params)
        name = getattr(self, "name", "validation")
        trainer.observation.update({f"{name}/{k}": v for k, v in obs.items()})
        return obs

    def __getattr__(self, item):
        return getattr(self._evaluator, item)


def create_multi_node_evaluator(actual_evaluator, communicator):
    """Reference-parity factory: returns the evaluator wrapped so its
    results are averaged over all processes."""
    return _MultiNodeEvaluator(actual_evaluator, communicator)


class GenericMultiNodeEvaluator(Evaluator):
    """Custom-aggregation variant (reference:
    ``chainermn/extensions/generic_multi_node_evaluator.py``): subclasses
    override ``aggregate`` to combine per-process results."""

    def __init__(self, comm, iterator, metrics_fn,
                 converter=default_converter, get_params=None):
        super().__init__(iterator, metrics_fn, comm, converter, get_params)

    def aggregate(self, results):
        out = {}
        for r in results:
            for k, v in r.items():
                out.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in out.items()}

    def evaluate(self, params):
        local = super().evaluate(params)
        gathered = self.comm.allgather_obj(local)
        return self.aggregate(gathered)
