"""Multi-node optimizer — analogue of ``chainermn.create_multi_node_optimizer``
and ``_DoubleBufferingOptimizer`` (reference: ``chainermn/optimizers.py``,
unverified — mount empty, see SURVEY.md).

The SURVEY §7 "hard part (a)": ChainerMN wrapped a mutable Chainer Optimizer
in an attribute-forwarding proxy that allreduced ``model.grads`` before
delegating.  JAX optimisers (optax) are pure gradient transformations inside
a jitted step — so the multi-node optimizer becomes a *transformation
stack*: ``[cast → cross-replica mean → cast back → inner optimiser]``.
There is no "first update broadcasts the weights" special case either:
parameters start replicated (``comm.bcast_data`` at init), which is the
first-call ``bcast_data(model)`` of the reference moved to where TPU wants
it.

Double buffering: the reference overlapped iteration *i*'s allreduce with
iteration *i+1*'s fwd/bwd using a worker thread and applied 1-step-stale
averaged grads.  On TPU the *overlap* is XLA's job (async collectives get
scheduled over independent compute automatically); what we preserve is the
**semantics** — applying 1-iteration-stale averaged gradients — because that
staleness is what unlocks the overlap window when the collective is on the
critical path.  Implemented as pure optax state (previous reduced grads),
no threads.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "PlannedOptimizer",
    "Zero1Transformation",
    "Zero2Transformation",
    "cross_replica_mean",
    "create_multi_node_optimizer",
    "shard_opt_state",
    "zero1_optimizer",
    "zero1_init",
    "zero2_optimizer",
    "DoubleBufferState",
]


def cross_replica_mean(
    axis_name: str,
    dtype=None,
    fused: bool = False,
    bucket_bytes: Optional[int] = None,
    inter_axis_name: Optional[str] = None,
) -> optax.GradientTransformation:
    """Optax transform: mean gradients across ``axis_name``.

    ``dtype`` is the ``allreduce_grad_dtype`` analogue — cast to (e.g.)
    bfloat16 for the wire, cast back after.  XLA fuses both casts into the
    collective's neighbourhood (the reference needed custom CuPy kernels for
    this; here it's free).

    ``fused=True`` routes the mean through
    :func:`chainermn_tpu.ops.fused_allreduce` — the grad pytree is packed
    into dtype-grouped flat buckets of ``bucket_bytes`` and reduced with
    one collective per bucket instead of one per leaf (the reference's
    ``batched_copy`` arena).  ``inter_axis_name`` additionally lowers each
    bucket hierarchically (reduce-scatter intra → all-reduce inter →
    all-gather intra) when the mesh has a second, slower axis.  The fused
    fp32 path is bit-identical to the per-leaf mean (elementwise sums over
    the same members); the compressed path carries the documented bf16
    tolerance.

    Semantics note (idempotency): under shard_map's varying-axes tracking,
    ``pmean`` of an already cross-replica-reduced (invariant) gradient is an
    identity, while ``pmean`` of a device-varying gradient is the true mean.
    So this transform is safe in both regimes: as the sole reducer when the
    user differentiates a *local* loss with grads entering as data, and as a
    no-op safety net when the step differentiates a ``pmean``'d loss (the
    StandardUpdater pattern, where shard_map AD already psums cotangents of
    replicated params).  "Mean of a mean is the mean" — the reference's
    allreduce had the same idempotent shape.

    Only meaningful inside ``shard_map`` (manual SPMD). Under plain
    ``pjit``/``jit`` with a batch-sharded loss *mean*, XLA already inserts
    the collective — then this transform must NOT be added (it would have
    no axis to reduce over).
    """

    def init(params):
        del params
        return optax.EmptyState()

    def update(grads, state, params=None):
        del params
        if fused:
            from chainermn_tpu.ops import fused as _fused

            return _fused.fused_allreduce(
                grads, axis_name, op="mean",
                bucket_bytes=bucket_bytes or _fused.DEFAULT_BUCKET_BYTES,
                wire_dtype=dtype,
                inter_axis_name=inter_axis_name,
            ), state

        def reduce_one(g):
            if dtype is not None and g.dtype != dtype:
                return jax.lax.pmean(g.astype(dtype), axis_name).astype(g.dtype)
            return jax.lax.pmean(g, axis_name)

        return jax.tree.map(reduce_one, grads), state

    return optax.GradientTransformation(init, update)


class PlannedOptimizer(NamedTuple):
    """A multi-node optimizer whose gradient exchange follows a TUNED
    plan (``utils/autotune.py``) instead of per-call kwargs.

    Structurally an ``optax.GradientTransformation`` (``init`` /
    ``update``); the extra ``plan_cell`` is the mutable
    :class:`~chainermn_tpu.utils.autotune.PlanCell` consumers read —
    ``StandardUpdater`` observes exchange times into it, the snapshot
    machinery persists ``plan_cell.plan`` so a resumed run compiles
    the identical exchange program (bitwise resume), never re-tunes
    into a different one.
    """

    init: Callable
    update: Callable
    plan_cell: Any


def _planned_mean(
    axis_name: str,
    cell,
    inter_axis_name: Optional[str] = None,
) -> optax.GradientTransformation:
    """Optax transform: mean gradients across ``axis_name`` following
    the resolved plan in ``cell`` (strategy × bucket size × wire dtype
    picked by measurement, not defaults).  The plan must be resolved
    BEFORE tracing — ``PlannedOptimizer.init`` does that eagerly."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(grads, state, params=None):
        del params
        plan = cell.plan
        if plan is None:
            raise RuntimeError(
                "exchange plan unresolved — call the planned "
                "optimizer's init(params) eagerly (outside jit) first; "
                "plan='auto' tunes there, where real probe programs "
                "can run")
        from chainermn_tpu.ops import fused as _fused

        return _fused.plan_allreduce(
            grads, axis_name, plan,
            inter_axis_name=inter_axis_name), state

    return optax.GradientTransformation(init, update)


class AccumState(NamedTuple):
    step: jnp.ndarray          # micro-step counter (same on all members)
    acc: optax.Updates         # running SUM of incoming (reduced) grads
    inner: Any


def _grad_accumulation(
    inner: optax.GradientTransformation, every: int,
    axis_name: Optional[str] = None,
) -> optax.GradientTransformation:
    """Gradient accumulation around ``inner``: parameters move every
    ``every`` calls with the mean of the accumulated grads.

    Not ``optax.MultiSteps``: its internal ``lax.cond`` branches return
    the incoming-typed updates on emit ticks but zeros typed from a
    fresh ``eval_shape`` on skip ticks, which shard_map's varying-axes
    typing rejects.  Here both branches type their outputs from the SAME
    values (``zeros_like`` of the accumulated mean / the untouched
    state), so the cond stays well-typed in every vma regime.  The
    factory feeds this transform already-reduced grads (post-pmean, or
    zero1 shards), so the accumulator is replication-typed (or
    shard-width); the value of accumulation is the ``every``×-larger
    global batch under fixed HBM — the cross-replica collectives still
    run per micro-step.  For the window-fused variant that also cuts
    collectives (and wire bytes) by ``every``×, use
    ``StandardUpdater(accum_steps=...)`` instead: the updater scans
    LOCAL microbatch gradients and lets this optimizer stack's reducer
    fire once per window.
    """

    def init(params):
        return AccumState(
            jnp.zeros((), jnp.int32),
            jax.tree.map(jnp.zeros_like, params),
            inner.init(params),
        )

    def update(grads, state, params=None):
        acc = jax.tree.map(lambda a, g: a + g, state.acc, grads)
        # the emit predicate must be replication-typed for the lax.cond
        # (a varying pred would force every output varying): the counter
        # is identical on all members by construction, but a world-
        # stacked zero1 carry types it varying — a scalar pmean restores
        # the invariant typing at negligible cost
        step = state.step
        if axis_name is not None:
            try:
                vma = jax.typeof(step).vma
            except AttributeError:  # pragma: no cover - older jax
                vma = ()
            if axis_name in vma:
                # the counter is identical on every member; pmax is an
                # EXACT int32 way to restore the replication typing the
                # cond predicate needs (a float pmean would lose integer
                # precision past 2**24 micro-steps)
                step = jax.lax.pmax(step, axis_name)
        emit = (step + 1) % every == 0
        mean = jax.tree.map(lambda a: a / every, acc)

        def do(mean, acc, inner_state):
            upd, new_inner = inner.update(mean, inner_state, params)
            return upd, jax.tree.map(jnp.zeros_like, acc), new_inner

        def skip(mean, acc, inner_state):
            # zeros typed from the SAME value the do branch feeds inner
            # (dtype and vma both match updates = inner.update(mean, ...))
            return jax.tree.map(jnp.zeros_like, mean), acc, inner_state

        upd, acc, new_inner = jax.lax.cond(
            emit, do, skip, mean, acc, state.inner)
        return upd, AccumState(state.step + 1, acc, new_inner)

    return optax.GradientTransformation(init, update)


class DoubleBufferState(NamedTuple):
    prev_grads: optax.Updates


def _double_buffer() -> optax.GradientTransformation:
    """Apply the *previous* step's (already reduced) grads; stash current.

    Matches the reference's pipelined-SGD semantics: weights at step t are
    updated with mean grads from step t-1 (step 0 applies the zero init),
    giving the scheduler a full step of slack to overlap the allreduce with
    compute.
    """

    def init(params):
        return DoubleBufferState(
            prev_grads=jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params=None):
        del params
        return state.prev_grads, DoubleBufferState(prev_grads=grads)

    return optax.GradientTransformation(init, update)


# --------------------------------------------------------------------- #
# ZeRO-1: optimizer-state sharding over the data axis
# --------------------------------------------------------------------- #


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


from chainermn_tpu.parallel._compat import (
    all_gather_invariant as _all_gather_invariant,
)
from chainermn_tpu.utils.programs import ledger_jit


def _ensure_varying(x, axis_name):
    """Mark ``x`` varying over ``axis_name`` if the type system considers
    it invariant (pre-reduced grads): psum_scatter of N identical copies
    divided by N is still the right mean, so both typings are correct."""
    try:
        vma = jax.typeof(x).vma
    except AttributeError:  # pragma: no cover - older jax: no vma typing
        return x
    if axis_name in vma:
        return x
    return jax.lax.pcast(x, axis_name, to="varying")


def _leaf_shard(leaf, idx, n: int):
    """This replica's 1-D shard of ``leaf`` (zero-padded to n·s)."""
    flat = leaf.reshape(-1)
    s = _ceil_div(flat.size, n)
    flat = jnp.pad(flat, (0, s * n - flat.size))
    return jax.lax.dynamic_slice(flat, (idx * s,), (s,))


class Zero1Transformation(NamedTuple):
    """An ``optax.GradientTransformation`` (structurally) whose distinct
    TYPE marks the ZeRO-1 state layout, so consumers that must carry the
    state differently (``StandardUpdater``: world-stacked, sharded over
    the data axis) can detect it instead of asking the user to repeat a
    ``zero1=True`` flag that could silently disagree.

    ``overlap`` marks that the owner asked for the backward-overlapped
    exchange: ZeRO-1's per-leaf ``psum_scatter``s are already join-free
    (each depends only on its own gradient leaf — the property the
    overlap lowering builds for the fused paths), so the flag's whole
    job is telling ``StandardUpdater`` to peel the window-final
    microbatch out of its accumulation scan, putting a backward pass
    in the outer program for those scatters to hide under."""

    init: Callable
    update: Callable
    overlap: bool = False


def zero1_optimizer(
    inner: optax.GradientTransformation,
    axis_name: str,
    wire_dtype=None,
    overlap: bool = False,
) -> optax.GradientTransformation:
    """ZeRO-1: shard ``inner``'s optimiser state across ``axis_name``.

    Beyond-reference (the reference replicated optimiser state on every
    rank, as every DP framework of its era did).  TPU-native mechanics —
    the whole thing is three collectives XLA schedules over ICI:

    - grads:    ``psum_scatter`` (mean) — each replica receives only its
                1/N slice of the averaged gradients, *cheaper on the wire
                than the pmean allreduce it replaces* (reduce-scatter is
                the first half of an allreduce);
    - update:   ``inner`` runs on the 1/N gradient shard with 1/N-sized
                state (Adam moments etc. cost ``2·P/N`` instead of ``2·P``);
    - params:   ``all_gather`` of the updated shard's *updates* (the
                second half of the allreduce), applied identically
                everywhere so parameters stay replicated.

    Must run inside ``shard_map`` with ``axis_name`` in scope — the same
    contract as :func:`cross_replica_mean` (init too: state shapes are
    per-shard).  ``inner`` must be *elementwise* (adam/sgd/adamw/...);
    transforms that mix elements across the tree (``clip_by_global_norm``)
    would see only the local shard and silently mis-normalise — compose
    those *before* this wrapper at full gradient width if needed.

    Each leaf is flattened and zero-padded to a multiple of the axis size;
    padded lanes run through ``inner`` (elementwise ⇒ garbage-in-padding
    stays in padding) and are dropped on the gather.
    """

    def init(params):
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        shards = jax.tree.map(lambda p: _leaf_shard(p, idx, n), params)
        return inner.init(shards)

    def update(grads, state, params=None):
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)

        def scatter_mean(g):
            flat = _ensure_varying(g.reshape(-1), axis_name)
            s = _ceil_div(flat.size, n)
            flat = jnp.pad(flat, (0, s * n - flat.size))
            if wire_dtype is not None and flat.dtype != wire_dtype:
                flat = flat.astype(wire_dtype)
                red = jax.lax.psum_scatter(flat, axis_name, tiled=True)
                return (red / n).astype(g.dtype)
            return jax.lax.psum_scatter(flat, axis_name, tiled=True) / n

        grad_shards = jax.tree.map(scatter_mean, grads)
        param_shards = None if params is None else jax.tree.map(
            lambda p: _leaf_shard(p, idx, n), params)
        upd_shards, state = inner.update(grad_shards, state, param_shards)

        def gather(u, ref):
            # all_gather_invariant: Varying -> Invariant, so the gathered
            # updates (identical on every member by construction) type as
            # replicated and the updated params stay invariant — the same
            # contract as the pmean path.  Its transpose is dynamic_slice,
            # exactly ZeRO's backward.
            if wire_dtype is not None and u.dtype != wire_dtype:
                full = _all_gather_invariant(
                    u.astype(wire_dtype), axis_name, tiled=True
                ).astype(u.dtype)
            else:
                full = _all_gather_invariant(u, axis_name, tiled=True)
            return full[: ref.size].reshape(ref.shape)

        return jax.tree.map(gather, upd_shards, grads), state

    return Zero1Transformation(init, update, overlap=bool(overlap))


# --------------------------------------------------------------------- #
# ZeRO-2: gradient + optimizer-state sharding over the data axis
# --------------------------------------------------------------------- #


class Zero2Transformation(NamedTuple):
    """Type-marks the ZeRO-2 layout the same way
    :class:`Zero1Transformation` marks ZeRO-1 — the optimizer STATE
    layout is identical (world-stacked 1/N flat shards; ``zero1_init``
    and the elastic/serialization machinery apply unchanged), what
    differs is the gradient exchange: per-BUCKET reduce-scatters over
    dtype-grouped leaf buckets instead of one collective per leaf, so
    the full-width averaged gradient never materializes and each
    bucket's scatter is join-free (depends only on its own leaves —
    the property the PR 7 backward-overlap stream needs).
    ``StandardUpdater`` carries ZeRO-2 state exactly like ZeRO-1."""

    init: Callable
    update: Callable
    overlap: bool = False


def _zero2_buckets(leaves, n: int, bucket_bytes: Optional[int]):
    """Join-free exchange buckets over flattened-order ``leaves``:
    grouped by dtype (a collective reduces one dtype), split so one
    bucket's PER-MEMBER shard stays under ``bucket_bytes`` (``None`` =
    one bucket per dtype).  Deterministic from tree order alone, so
    every member builds the identical program."""
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    buckets = []
    for dt, idxs in by_dtype.items():
        cur, cur_b = [], 0
        for i in idxs:
            b = _ceil_div(leaves[i].size, n) * dt.itemsize
            if cur and bucket_bytes is not None \
                    and cur_b + b > bucket_bytes:
                buckets.append((dt, cur))
                cur, cur_b = [], 0
            cur.append(i)
            cur_b += b
        if cur:
            buckets.append((dt, cur))
    return buckets


def zero2_optimizer(
    inner: optax.GradientTransformation,
    axis_name: str,
    wire_dtype=None,
    overlap: bool = False,
    bucket_bytes: Optional[int] = None,
) -> optax.GradientTransformation:
    """ZeRO-2: shard gradients AND ``inner``'s optimiser state across
    ``axis_name``.

    ZeRO-1 (:func:`zero1_optimizer`) already never materializes the
    full averaged gradient — its per-leaf ``psum_scatter`` IS the
    exchange.  ZeRO-2 keeps the exact same state layout (flat 1/N
    shards per leaf — ``zero1_init``, ``relayout_state`` and the
    shard-only snapshots all apply verbatim) and upgrades the exchange
    to the BUCKETED form: leaves are packed member-major into
    dtype-grouped buckets (each leaf padded to ``n·s`` and reshaped
    ``(n, s)``, buckets concatenated along the shard axis), one
    reduce-scatter per bucket, then sliced back into per-leaf shards.
    Per-element the sums cross the same members in the same order, so
    the fp32 shards are BITWISE identical to ZeRO-1's — the win is
    collective count (L leaves → B buckets) plus join-free buckets the
    backward-overlap stream can hide one at a time.

    Same contract as :func:`zero1_optimizer`: run inside ``shard_map``,
    ``inner`` must be elementwise, padded lanes stay garbage-in-padding.
    ``bucket_bytes`` caps one bucket's per-member shard bytes
    (``utils.comm_model.choose_bucket_bytes`` picks a principled value);
    ``None`` packs each dtype whole.
    """

    def init(params):
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        shards = jax.tree.map(lambda p: _leaf_shard(p, idx, n), params)
        return inner.init(shards)

    def update(grads, state, params=None):
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        widths = [_ceil_div(l.size, n) for l in leaves]

        # -- bucketed reduce-scatter: the gradient exchange ---------- #
        shard_leaves = [None] * len(leaves)
        for dt, idxs in _zero2_buckets(leaves, n, bucket_bytes):
            mats = []
            for i in idxs:
                flat = _ensure_varying(leaves[i].reshape(-1), axis_name)
                flat = jnp.pad(flat, (0, widths[i] * n - flat.size))
                mats.append(flat.reshape(n, widths[i]))
            buf = (mats[0] if len(mats) == 1
                   else jnp.concatenate(mats, axis=1)).reshape(-1)
            if wire_dtype is not None and buf.dtype != wire_dtype:
                red = jax.lax.psum_scatter(
                    buf.astype(wire_dtype), axis_name, tiled=True)
                red = (red / n).astype(dt)
            else:
                red = jax.lax.psum_scatter(buf, axis_name,
                                           tiled=True) / n
            off = 0
            for i in idxs:
                shard_leaves[i] = red[off:off + widths[i]]
                off += widths[i]
        grad_shards = treedef.unflatten(shard_leaves)

        param_shards = None if params is None else jax.tree.map(
            lambda p: _leaf_shard(p, idx, n), params)
        upd_shards, state = inner.update(grad_shards, state,
                                         param_shards)

        # -- bucketed gather of the updates -------------------------- #
        upd_leaves = jax.tree.leaves(upd_shards)
        out = [None] * len(leaves)
        for dt, idxs in _zero2_buckets(upd_leaves, n, bucket_bytes):
            cat = (upd_leaves[idxs[0]] if len(idxs) == 1
                   else jnp.concatenate([upd_leaves[i] for i in idxs]))
            if wire_dtype is not None and cat.dtype != wire_dtype:
                full = _all_gather_invariant(
                    cat.astype(wire_dtype), axis_name,
                    tiled=True).astype(dt)
            else:
                full = _all_gather_invariant(cat, axis_name, tiled=True)
            mat = full.reshape(n, cat.size)
            off = 0
            for i in idxs:
                ref = leaves[i]
                out[i] = mat[:, off:off + widths[i]].reshape(
                    -1)[: ref.size].reshape(ref.shape)
                off += widths[i]
        return treedef.unflatten(out), state

    return Zero2Transformation(init, update, overlap=bool(overlap))


def shard_opt_state(optimizer, params):
    """Initialise ``optimizer``'s state with the PARAMS' shardings.

    ``jax.jit(optimizer.init)(params)`` silently replicates the state:
    ``zeros_like`` has no data dependence on its input, so XLA's
    sharding propagation never reaches the moment buffers — under an
    FSDP/ZeRO-3 param layout that re-materialises ``2·P`` of replicated
    Adam state and forfeits the sharding's memory win (and forces a
    reshard on the first update).  This helper pins ``out_shardings``
    instead: each state leaf whose shape matches a param leaf gets that
    param's sharding (elementwise optimiser state mirrors the param
    tree leaf-for-leaf), scalars and unmatched leaves replicate.

    Works for any placed param pytree (transformer, ResNet, custom);
    falls back to plain ``jit(init)`` for uncommitted host arrays.

    Matching: optax's params-shaped state (``mu``/``nu``/trace/...)
    mirrors the param tree structurally, so each state leaf's tree path
    *ends with* some param leaf's full path (``mu.blocks.w1`` ↔
    ``blocks.w1``) — longest matching path suffix with an equal shape
    wins; scalars and unmatched leaves replicate.  No shape-only
    fallback: two same-shape params can carry different shardings
    (fsdp w1/w2 with d_ff == d_model), and guessing would pin a
    transposed layout that costs a hidden reshard every update —
    replicated is the safe default for state a path can't identify.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import tree_flatten_with_path

    p_paths, _ = tree_flatten_with_path(params)
    by_path, mesh = {}, None
    for path, p in p_paths:
        sh = getattr(p, "sharding", None)
        if sh is None or not hasattr(sh, "mesh"):
            continue
        mesh = mesh if mesh is not None else sh.mesh
        by_path[tuple(str(k) for k in path)] = (p.shape, sh)
    if mesh is None:
        return ledger_jit(optimizer.init,
                          label="train/opt_init")(params)
    replicated = NamedSharding(mesh, P())
    shapes = jax.eval_shape(optimizer.init, params)
    s_paths, treedef = tree_flatten_with_path(shapes)

    def pick(path, sd):
        keys = tuple(str(k) for k in path)
        # longest suffix first, INCLUDING the empty suffix — a bare
        # jax.Array params "tree" has the empty path as its only key
        for start in range(len(keys) + 1):
            hit = by_path.get(keys[start:])
            if hit is not None and hit[0] == sd.shape:
                return hit[1]
        return replicated

    out_shardings = treedef.unflatten(
        [pick(path, sd) for path, sd in s_paths])
    return ledger_jit(optimizer.init, label="train/opt_init",
                      out_shardings=out_shardings)(params)


def zero1_init(tx, params, mesh, axis_name: str):
    """Initialise a :func:`zero1_optimizer`-wrapped transformation whose
    state must persist *across* jit/shard_map boundaries.

    ``tx.init`` needs the mesh axis in scope (state shapes are per-shard),
    so ``jax.jit(tx.init)(params)`` does not work for ZeRO.  This helper
    runs init inside ``shard_map`` and returns **world-stacked** state
    (leading axis = member index along ``axis_name``, the same convention
    as the eager communicator collectives): every leaf — including rank-0
    leaves like adam's ``count`` — gets a leading member axis so one
    uniform ``P(axis_name)`` spec moves it through any boundary.

    Step functions receive the stacked state with ``in_specs
    P(axis_name)`` (each member sees its own ``(1, ...)`` slice), drop the
    member axis with ``jax.tree.map(lambda x: x[0], state)``, run
    ``tx.update``, re-stack with ``jax.tree.map(lambda x: x[None], st)``
    and return it under ``out_specs P(axis_name)``.
    """
    from jax.sharding import PartitionSpec as P

    def body(p):
        state = tx.init(p)
        # member axis on every leaf; varying-typed so P(axis_name) is
        # always a legal (and shape-unambiguous) out_spec
        return jax.tree.map(
            lambda x: _ensure_varying(jnp.asarray(x), axis_name)[None],
            state)

    f = ledger_jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(), out_specs=P(axis_name)),
        label="train/opt_init")
    return f(params)


# one-time (per process) warning for plan= under ZeRO-1 — the fallback
# must be visible, not a silent downgrade, but not a per-step nag either
_ZERO1_PLAN_WARNED = False


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    comm=None,
    double_buffering: bool = False,
    zero1: bool = False,
    zero2: bool = False,
    accum_steps: int = 1,
    axis_name: Optional[str] = None,
    allreduce_grad_dtype=None,
    fused: bool = True,
    bucket_bytes: Optional[int] = None,
    inter_axis_name: Optional[str] = None,
    plan=None,
    overlap: Any = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimiser with cross-replica gradient averaging.

    Args:
      actual_optimizer: any ``optax.GradientTransformation`` (the reference
        wrapped any Chainer ``Optimizer`` the same way).
      comm: communicator whose ``axis_name`` defines the reduction axis
        (or pass ``axis_name`` directly).
      double_buffering: apply 1-step-stale reduced grads (overlap window —
        reference's ``_DoubleBufferingOptimizer``).
      zero1: shard optimiser state over the reduction axis
        (:func:`zero1_optimizer`); replaces the pmean with a
        reduce-scatter/all-gather pair.  With ``double_buffering`` the
        stale-grad stash is also sharded (1/N memory).
      zero2: ZeRO-2 (:func:`zero2_optimizer`) — same optimiser-state
        layout as ``zero1`` (the updater/elastic/snapshot machinery is
        shared), with the gradient exchange bucketed: dtype-grouped
        join-free reduce-scatters instead of one collective per leaf,
        so gradients too live at 1/N width between scatter and gather.
        Mutually exclusive with ``zero1``; ``bucket_bytes`` caps the
        per-member bucket shard.
      accum_steps: gradient accumulation — parameters update every
        ``accum_steps`` calls with the mean of the accumulated grads
        (global batch = ``world × local_batch × accum_steps``; the
        large-batch recipe's missing piece when HBM caps the per-step
        batch).  The accumulator sits after the cross-replica reduction,
        so it holds *reduced* (replication-typed) grads — carryable with
        plain replicated out_specs in every regime — and, under zero1,
        1/world-width shards.  Double buffering composes at the emit
        level (staleness counts real updates, not micro-steps).  NOTE:
        the collectives still fire per micro-step here; prefer
        ``StandardUpdater(accum_steps=...)`` (window-fused exchange,
        M→1 collectives per window) unless grads really do arrive one
        external call at a time.  Don't stack both: each would divide
        by its own window.
      allreduce_grad_dtype: wire dtype for the mean (bf16 recommended).
      fused: pack the grad pytree into flat dtype-grouped buckets and
        reduce one bucket per collective
        (:func:`chainermn_tpu.ops.fused_allreduce`) instead of one
        collective per leaf — the default, and numerically identical to
        per-leaf pmean in fp32.  Ignored under ``zero1`` (whose
        reduce-scatter/all-gather pair already amortises per-leaf).
      bucket_bytes: fused bucket size;
        :func:`chainermn_tpu.utils.comm_model.choose_bucket_bytes` picks
        a principled value from the latency-bandwidth model (default
        4 MiB).
      inter_axis_name: second (slower, e.g. DCN) mesh axis for the
        hierarchical 2-stage bucket lowering; the step's ``shard_map``
        must bind both axes.  Typically wired by the communicator when
        ``comm.inter_size > 1``.
      plan: drive the gradient exchange from a MEASURED plan
        (``utils/autotune.py``) instead of the kwargs above.
        ``"auto"`` tunes at ``init(params)`` time (eager, outside jit
        — the ``StandardUpdater`` contract): cache warm-start when the
        (mesh, payload, version) signature matches, otherwise a live
        probe search whose winner rank 0 broadcasts; a
        :class:`~chainermn_tpu.utils.autotune.Plan` (or its dict form,
        e.g. restored from a snapshot) skips tuning entirely.  Returns
        a :class:`PlannedOptimizer` carrying the ``plan_cell``; the
        ``fused``/``bucket_bytes``/``allreduce_grad_dtype`` kwargs are
        superseded by the plan's strategy/bucket/wire fields.
        Hierarchical candidates enter the search only when
        ``inter_axis_name`` is given (the step must bind the axis).
        Under ``zero1`` the plan is IGNORED with a one-time warning:
        ZeRO-1's reduce-scatter/all-gather pair is a different exchange
        family the planner does not drive, and the analytic path is
        the correct fallback — so ``plan="auto"`` is safe to set
        globally across a fleet where some jobs shard their optimizer
        state.
      overlap: fire the gradient exchange DURING the backward pass
        instead of after it (the backward-overlapped lowering,
        ``ops.fused.overlap_exchange``): the grad pytree is cut into
        reverse-layer-ordered buckets and each bucket's
        reduce-scatter→all-gather is emitted as soon as its gradients
        exist, so XLA hides wire time under the remaining backward
        compute (``utils.comm_model.assert_overlap_collectives`` is
        the HLO proof).  ``True`` with ``plan=None`` builds a static
        overlap plan (analytic schedule from ``bucket_bytes`` /
        ``allreduce_grad_dtype``); with ``plan="auto"`` the autotuner
        searches the *schedule* dimension (bucket boundaries ×
        eager/deferred per bucket) and the winner stays in the overlap
        family; ``"auto"`` (with ``plan="auto"``) lets measurement
        pick between the overlap and window-end families.  Under
        ``zero1`` the per-leaf reduce-scatters are already join-free,
        so the flag only marks the transformation for the updater's
        final-microbatch peel.  ``StandardUpdater`` detects overlap
        from the plan and restructures its accumulation scan so the
        window-final microbatch's backward sits in the outer program —
        otherwise the scan would join every gradient and there would
        be nothing to overlap under.
    """
    ax = axis_name or (comm.axis_name if comm is not None else None)
    if ax is None:
        raise ValueError("need comm or axis_name")
    if accum_steps < 1:
        raise ValueError(f"accum_steps {accum_steps} must be >= 1")
    if zero1 and zero2:
        raise ValueError(
            "zero1=True and zero2=True are mutually exclusive — "
            "ZeRO-2 subsumes ZeRO-1's state sharding; pick one")
    if plan is not None and (zero1 or zero2):
        # graceful fallback, not an error: plan="auto" must be safe to
        # set globally.  ZeRO-1's reduce-scatter/all-gather pair is its
        # own (analytic, per-leaf, join-free) exchange; the plan would
        # drive an exchange that never runs.
        global _ZERO1_PLAN_WARNED
        if not _ZERO1_PLAN_WARNED:
            _ZERO1_PLAN_WARNED = True
            warnings.warn(
                "create_multi_node_optimizer: plan= is ignored under "
                "zero1/zero2 — ZeRO exchanges gradients through its "
                "own reduce-scatter/all-gather pair, so the analytic "
                "path is used instead of the tuned plan (warning shown "
                "once per process)", RuntimeWarning, stacklevel=2)
        plan = None
    inner = actual_optimizer
    if double_buffering:
        inner = optax.chain(_double_buffer(), inner)
    if accum_steps > 1:
        inner = _grad_accumulation(inner, accum_steps, axis_name=ax)
    if zero2:
        # accumulation INSIDE zero2: the accumulator holds 1/N shards
        return zero2_optimizer(inner, ax,
                               wire_dtype=allreduce_grad_dtype,
                               overlap=bool(overlap),
                               bucket_bytes=bucket_bytes)
    if zero1:
        # accumulation INSIDE zero1: the accumulator holds 1/N shards
        return zero1_optimizer(inner, ax,
                               wire_dtype=allreduce_grad_dtype,
                               overlap=bool(overlap))
    if overlap and plan is None:
        if overlap is not True:
            # overlap="auto" means "let the MEASUREMENT pick between
            # the overlap and window-end families" — without
            # plan="auto" no measurement ever runs, and silently
            # forcing the static overlap plan would contradict the
            # request
            raise ValueError(
                f"overlap={overlap!r} asks the measured search to "
                f"choose between the overlap and window-end families, "
                f"which needs plan='auto'; pass overlap=True for the "
                f"static (untuned) overlap plan")
        # static overlap plan: analytic schedule derived from
        # bucket_bytes at trace time, no tuning, no comm needed
        from chainermn_tpu.ops import fused as _fused
        from chainermn_tpu.utils import autotune as _autotune

        plan = _autotune.Plan(
            strategy="overlap",
            bucket_bytes=bucket_bytes or _fused.DEFAULT_BUCKET_BYTES,
            wire_dtype=(jnp.dtype(allreduce_grad_dtype).name
                        if allreduce_grad_dtype is not None else None),
        )
    if plan is not None:
        from chainermn_tpu.utils import autotune as _autotune

        if isinstance(plan, _autotune.PlanCell):
            cell = plan
        elif isinstance(plan, str):
            if plan != "auto":
                raise ValueError(
                    f"plan={plan!r}: expected 'auto', a Plan, or a "
                    f"plan dict")
            if comm is None:
                raise ValueError(
                    "plan='auto' needs comm — the autotuner probes on "
                    "its mesh and broadcasts the winner from rank 0")
            cell = _autotune.PlanCell()
        else:
            cell = _autotune.PlanCell(_autotune.Plan.from_any(plan))
        if overlap is True and cell.plan is not None \
                and cell.plan.strategy != "overlap":
            raise ValueError(
                f"overlap=True with an explicit plan of strategy "
                f"{cell.plan.strategy!r}: the plan drives the exchange, "
                f"so a window-end plan cannot satisfy the overlap "
                f"request — pass an 'overlap' plan, plan='auto', or "
                f"drop overlap=")
        chained = optax.chain(
            _planned_mean(ax, cell, inter_axis_name=inter_axis_name),
            inner)

        # the plan executes inside the USER's shard_map: hierarchical
        # is only runnable when that program binds the second axis.
        # Recorded on the cell so a later drift retune() tunes under
        # the SAME constraint (including the overlap-family one).
        cell.tune_kwargs = dict(
            inter_axis_name=inter_axis_name,
            allow_hierarchical=(
                None if inter_axis_name is not None else False),
            overlap=overlap if overlap else False)

        def planned_init(params):
            if cell.plan is None:
                cell.resolve(_autotune.autotune_plan(
                    comm, params, **cell.tune_kwargs))
            return chained.init(params)

        return PlannedOptimizer(planned_init, chained.update, cell)
    return optax.chain(
        cross_replica_mean(ax, allreduce_grad_dtype, fused=fused,
                           bucket_bytes=bucket_bytes,
                           inter_axis_name=inter_axis_name), inner)
