"""Multi-node optimizer — analogue of ``chainermn.create_multi_node_optimizer``
and ``_DoubleBufferingOptimizer`` (reference: ``chainermn/optimizers.py``,
unverified — mount empty, see SURVEY.md).

The SURVEY §7 "hard part (a)": ChainerMN wrapped a mutable Chainer Optimizer
in an attribute-forwarding proxy that allreduced ``model.grads`` before
delegating.  JAX optimisers (optax) are pure gradient transformations inside
a jitted step — so the multi-node optimizer becomes a *transformation
stack*: ``[cast → cross-replica mean → cast back → inner optimiser]``.
There is no "first update broadcasts the weights" special case either:
parameters start replicated (``comm.bcast_data`` at init), which is the
first-call ``bcast_data(model)`` of the reference moved to where TPU wants
it.

Double buffering: the reference overlapped iteration *i*'s allreduce with
iteration *i+1*'s fwd/bwd using a worker thread and applied 1-step-stale
averaged grads.  On TPU the *overlap* is XLA's job (async collectives get
scheduled over independent compute automatically); what we preserve is the
**semantics** — applying 1-iteration-stale averaged gradients — because that
staleness is what unlocks the overlap window when the collective is on the
critical path.  Implemented as pure optax state (previous reduced grads),
no threads.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "cross_replica_mean",
    "create_multi_node_optimizer",
    "DoubleBufferState",
]


def cross_replica_mean(axis_name: str, dtype=None) -> optax.GradientTransformation:
    """Optax transform: mean gradients across ``axis_name``.

    ``dtype`` is the ``allreduce_grad_dtype`` analogue — cast to (e.g.)
    bfloat16 for the wire, cast back after.  XLA fuses both casts into the
    collective's neighbourhood (the reference needed custom CuPy kernels for
    this; here it's free).

    Semantics note (idempotency): under shard_map's varying-axes tracking,
    ``pmean`` of an already cross-replica-reduced (invariant) gradient is an
    identity, while ``pmean`` of a device-varying gradient is the true mean.
    So this transform is safe in both regimes: as the sole reducer when the
    user differentiates a *local* loss with grads entering as data, and as a
    no-op safety net when the step differentiates a ``pmean``'d loss (the
    StandardUpdater pattern, where shard_map AD already psums cotangents of
    replicated params).  "Mean of a mean is the mean" — the reference's
    allreduce had the same idempotent shape.

    Only meaningful inside ``shard_map`` (manual SPMD). Under plain
    ``pjit``/``jit`` with a batch-sharded loss *mean*, XLA already inserts
    the collective — then this transform must NOT be added (it would have
    no axis to reduce over).
    """

    def init(params):
        del params
        return optax.EmptyState()

    def update(grads, state, params=None):
        del params

        def reduce_one(g):
            if dtype is not None and g.dtype != dtype:
                return jax.lax.pmean(g.astype(dtype), axis_name).astype(g.dtype)
            return jax.lax.pmean(g, axis_name)

        return jax.tree.map(reduce_one, grads), state

    return optax.GradientTransformation(init, update)


class DoubleBufferState(NamedTuple):
    prev_grads: optax.Updates


def _double_buffer() -> optax.GradientTransformation:
    """Apply the *previous* step's (already reduced) grads; stash current.

    Matches the reference's pipelined-SGD semantics: weights at step t are
    updated with mean grads from step t-1 (step 0 applies the zero init),
    giving the scheduler a full step of slack to overlap the allreduce with
    compute.
    """

    def init(params):
        return DoubleBufferState(
            prev_grads=jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params=None):
        del params
        return state.prev_grads, DoubleBufferState(prev_grads=grads)

    return optax.GradientTransformation(init, update)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    comm=None,
    double_buffering: bool = False,
    zero_loss_scale: Optional[float] = None,
    axis_name: Optional[str] = None,
    allreduce_grad_dtype=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimiser with cross-replica gradient averaging.

    Args:
      actual_optimizer: any ``optax.GradientTransformation`` (the reference
        wrapped any Chainer ``Optimizer`` the same way).
      comm: communicator whose ``axis_name`` defines the reduction axis
        (or pass ``axis_name`` directly).
      double_buffering: apply 1-step-stale reduced grads (overlap window —
        reference's ``_DoubleBufferingOptimizer``).
      allreduce_grad_dtype: wire dtype for the mean (bf16 recommended).
    """
    ax = axis_name or (comm.axis_name if comm is not None else None)
    if ax is None:
        raise ValueError("need comm or axis_name")
    chain = [cross_replica_mean(ax, allreduce_grad_dtype)]
    if double_buffering:
        chain.append(_double_buffer())
    chain.append(actual_optimizer)
    del zero_loss_scale  # reserved
    return optax.chain(*chain)
