"""Training integration layer (reference L3/L5: optimizers, evaluators,
trainer extension protocol)."""

from .elastic import (
    ElasticMembership,
    MembershipRecord,
    RelayoutError,
    StaleGenerationError,
    relayout_state,
    same_topology,
    topology_signature,
)
from .evaluators import (
    Evaluator,
    GenericMultiNodeEvaluator,
    create_multi_node_evaluator,
)
from .optimizers import (
    PlannedOptimizer,
    create_multi_node_optimizer,
    cross_replica_mean,
    shard_opt_state,
    zero1_init,
    zero1_optimizer,
    zero2_optimizer,
)
from .trainer import LogReport, PrintReport, Trainer, make_extension
from .triggers import IntervalTrigger, get_trigger
from .updater import StandardUpdater, default_converter, fuse_steps

__all__ = [
    "ElasticMembership",
    "Evaluator",
    "MembershipRecord",
    "PlannedOptimizer",
    "GenericMultiNodeEvaluator",
    "IntervalTrigger",
    "LogReport",
    "PrintReport",
    "RelayoutError",
    "StaleGenerationError",
    "StandardUpdater",
    "Trainer",
    "relayout_state",
    "same_topology",
    "topology_signature",
    "create_multi_node_evaluator",
    "create_multi_node_optimizer",
    "cross_replica_mean",
    "default_converter",
    "fuse_steps",
    "get_trigger",
    "make_extension",
    "shard_opt_state",
    "zero1_init",
    "zero1_optimizer",
    "zero2_optimizer",
]
