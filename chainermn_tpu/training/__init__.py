"""Training integration layer (reference L3/L5: optimizers, evaluators,
trainer extension protocol)."""

from .evaluators import (
    Evaluator,
    GenericMultiNodeEvaluator,
    create_multi_node_evaluator,
)
from .optimizers import (
    PlannedOptimizer,
    create_multi_node_optimizer,
    cross_replica_mean,
    shard_opt_state,
    zero1_init,
    zero1_optimizer,
)
from .trainer import LogReport, PrintReport, Trainer, make_extension
from .triggers import IntervalTrigger, get_trigger
from .updater import StandardUpdater, default_converter, fuse_steps

__all__ = [
    "Evaluator",
    "PlannedOptimizer",
    "GenericMultiNodeEvaluator",
    "IntervalTrigger",
    "LogReport",
    "PrintReport",
    "StandardUpdater",
    "Trainer",
    "create_multi_node_evaluator",
    "create_multi_node_optimizer",
    "cross_replica_mean",
    "default_converter",
    "fuse_steps",
    "get_trigger",
    "make_extension",
    "shard_opt_state",
    "zero1_init",
    "zero1_optimizer",
]
