"""Full-trainer resume state — shared by the checkpointer and
multi_node_snapshot.

The reference serialized the whole trainer object graph through
``chainer.serializers`` (SURVEY.md §3.5), so a resumed run continued its
epoch, shuffle order, and log exactly.  The round-1 build saved only
``{iteration, params, opt_state, model_state}`` — a resumed run silently
restarted its epoch and lost its log history.  These helpers collect and
restore the rest:

- updater bookkeeping (``epoch_detail`` drives epoch triggers),
- the training iterator's position/epoch/RNG (``state_dict`` protocol),
- every trainer extension exposing ``state_dict``/``load_state_dict``
  (LogReport history, custom extensions), keyed by extension name,
- the wall-clock offset, so the logged timeline continues.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["collect_train_state", "restore_train_state"]


def collect_train_state(updater, trainer=None) -> dict:
    """Everything beyond (params, opt_state, model_state) a resume needs."""
    extra: dict = {
        "updater": {
            "epoch_detail": float(getattr(updater, "epoch_detail", 0.0)),
            "previous_epoch_detail": float(
                getattr(updater, "previous_epoch_detail", 0.0)),
        },
    }
    it = getattr(updater, "iterator", None)
    if it is not None and hasattr(it, "state_dict"):
        extra["iterator"] = it.state_dict()
    cell = getattr(getattr(updater, "optimizer", None), "plan_cell", None)
    if cell is not None and cell.plan is not None:
        # the tuned exchange plan rides the snapshot: a resumed run must
        # compile the IDENTICAL exchange program (bitwise resume), never
        # re-tune into a different one because the plan cache moved
        extra["exchange_plan"] = cell.plan.to_dict()
    if trainer is not None:
        exts = {}
        for entry in getattr(trainer, "_extensions", []):
            sd = getattr(entry.ext, "state_dict", None)
            if sd is not None:
                exts[entry.name] = sd()
        extra["trainer"] = {
            "elapsed_time": float(getattr(trainer, "elapsed_time", 0.0)),
            "extensions": exts,
        }
    return extra


def restore_train_state(extra: Optional[dict], updater,
                        trainer=None) -> None:
    """Inverse of :func:`collect_train_state`; tolerates snapshots written
    before a given piece of state existed (partial restores)."""
    if not extra:
        return
    up = extra.get("updater", {})
    if "epoch_detail" in up:
        updater.epoch_detail = float(up["epoch_detail"])
    if "previous_epoch_detail" in up:
        updater.previous_epoch_detail = float(up["previous_epoch_detail"])
    it = getattr(updater, "iterator", None)
    if it is not None and hasattr(it, "load_state_dict") \
            and "iterator" in extra:
        saved = extra["iterator"]
        order = saved.get("order")
        ds = getattr(it, "dataset", None)
        # example count, not len(dataset): for tuple-of-field-arrays
        # fast-path datasets len() counts fields
        n_examples = getattr(it, "dataset_length", None)
        if n_examples is None and ds is not None:
            n_examples = len(ds)
        if order is not None and n_examples is not None \
                and len(order) != n_examples:
            # resize-safe path (multi_node_snapshot at a different world
            # size): the saved shuffle order indexes the WRITER's dataset
            # shard — restoring it onto a differently-sized shard would
            # read out of bounds / wrong examples.  Keep the fresh
            # iterator (epoch restarts; params/opt state still resume).
            pass
        else:
            it.load_state_dict(saved)
    if "exchange_plan" in extra:
        cell = getattr(getattr(updater, "optimizer", None), "plan_cell",
                       None)
        if cell is not None:
            from chainermn_tpu.utils.autotune import Plan

            saved_plan = Plan.from_dict(extra["exchange_plan"])

            def _exec_fields(p):
                # only the fields plan_allreduce actually reads decide
                # program identity; meta (timings, timestamps) differing
                # must not force a pointless recompile of an execution-
                # identical plan at resume
                return (p.strategy, int(p.bucket_bytes), p.wire_dtype)

            if cell.plan is None or \
                    _exec_fields(cell.plan) != _exec_fields(saved_plan):
                # adopt the WRITER's plan so the resumed run compiles
                # the identical exchange program; programs that already
                # baked the fresh-tuned plan in must recompile
                cell.resolve(saved_plan)
                cache = getattr(updater, "_step_cache", None)
                if isinstance(cache, dict):
                    cache.clear()
    if trainer is not None and "trainer" in extra:
        tr = extra["trainer"]
        trainer.elapsed_time = float(tr.get("elapsed_time", 0.0))
        saved = tr.get("extensions", {})
        for entry in getattr(trainer, "_extensions", []):
            if entry.name in saved and hasattr(entry.ext, "load_state_dict"):
                entry.ext.load_state_dict(saved[entry.name])
