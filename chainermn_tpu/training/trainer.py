"""Trainer — host-side training loop with the extension protocol the
reference's L5 subsystems (checkpointer, snapshot, aggregator, LogReport)
plug into.  Minimal but real: interval triggers, prioritised extensions,
an observation dict per iteration, and rank-0-aware reporting.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from .triggers import get_trigger

__all__ = ["Trainer", "LogReport", "PrintReport", "make_extension"]


class _ExtensionEntry:
    def __init__(self, ext, trigger, name, priority):
        self.ext = ext
        self.trigger = get_trigger(trigger)
        self.name = name
        self.priority = priority


def make_extension(trigger=(1, "epoch"), priority=100):
    """Decorator marking a function as a trainer extension (parity with
    ``chainer.training.make_extension``)."""

    def wrap(fn):
        fn.trigger = trigger
        fn.priority = priority
        return fn

    return wrap


class Trainer:
    def __init__(self, updater, stop_trigger, out: str = "result"):
        self.updater = updater
        period, unit = stop_trigger
        self._stop_period = period
        self._stop_unit = unit
        self.out = out
        self._extensions = []
        self.observation = {}
        self.elapsed_time = 0.0
        self._start = None
        self._stop_requested = False
        self.stop_reason = None

    def stop(self, reason: str = None):
        """Request a clean stop: the loop exits after the current
        iteration's extensions run (used by preemption handling)."""
        self._stop_requested = True
        self.stop_reason = reason

    def extend(self, extension, trigger=None, name=None, priority=None):
        trig = trigger if trigger is not None else getattr(
            extension, "trigger", (1, "epoch"))
        prio = priority if priority is not None else getattr(
            extension, "priority", 100)
        nm = name or getattr(extension, "name", None) or getattr(
            extension, "__name__", type(extension).__name__)
        self._extensions.append(_ExtensionEntry(extension, trig, nm, prio))
        self._extensions.sort(key=lambda e: -e.priority)
        return self

    def _done(self) -> bool:
        if self._stop_requested:
            return True
        if self._stop_unit == "epoch":
            return self.updater.epoch_detail >= self._stop_period
        return self.updater.iteration >= self._stop_period

    def run(self):
        # resume-aware clock: a restored elapsed_time offsets the start so
        # the logged timeline continues instead of restarting at zero
        self._start = time.perf_counter() - self.elapsed_time
        os.makedirs(self.out, exist_ok=True)
        # initialize-phase extensions (e.g. checkpointer.maybe_load ran
        # before run(); extensions with an initialize hook fire here)
        for e in self._extensions:
            init = getattr(e.ext, "initialize", None)
            if init:
                init(self)
            trig_init = getattr(e.trigger, "initialize", None)
            if trig_init:
                trig_init(self)
        try:
            while not self._done():
                self.updater.update()
                self.observation = dict(self.updater.observation)
                self.elapsed_time = time.perf_counter() - self._start
                for e in self._extensions:
                    # extensions with an ``observe`` hook see EVERY
                    # iteration's observation (LogReport interval
                    # averaging); ``__call__`` still fires on the trigger
                    obs_hook = getattr(e.ext, "observe", None)
                    if obs_hook:
                        obs_hook(self)
                for e in self._extensions:
                    if e.trigger(self):
                        e.ext(self)
        finally:
            # finalize even when update() raises: an in-flight async
            # checkpoint write must not be lost to the crash it exists
            # to protect against
            for e in self._extensions:
                fin = getattr(e.ext, "finalize", None)
                if fin:
                    fin(self)
            # release the updater's feed (joins a prefetching
            # iterator's worker thread; restarts transparently if
            # run() is called again)
            up_fin = getattr(self.updater, "finalize", None)
            if up_fin:
                up_fin()


class LogReport:
    """Collects observations into ``out/log`` (JSON list), averaging scalar
    entries over the report interval — rank-0 printing stays the user's
    choice exactly as in the reference examples."""

    def __init__(self, trigger=(1, "epoch"), filename: str = "log"):
        self.trigger = trigger
        self.priority = 50
        self._filename = filename
        self._accum = {}
        self._count = 0
        self.log = []

    def observe(self, trainer):
        """Called by the trainer every iteration (interval accumulation)."""
        for k, v in trainer.observation.items():
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            self._accum[k] = self._accum.get(k, 0.0) + f
        self._count += 1

    def state_dict(self) -> dict:
        return {"log": list(self.log), "accum": dict(self._accum),
                "count": self._count}

    def load_state_dict(self, st: dict) -> None:
        self.log = [dict(e) for e in st["log"]]
        self._accum = {k: float(v) for k, v in st["accum"].items()}
        self._count = int(st["count"])

    def __call__(self, trainer):
        # average of every observation since the last fire
        entry = {k: v / max(self._count, 1) for k, v in self._accum.items()}
        # plus values produced at trigger time by earlier-priority
        # extensions this same fire (e.g. the evaluator's validation/*)
        for k, v in trainer.observation.items():
            if k not in entry:
                try:
                    entry[k] = float(v)
                except (TypeError, ValueError):
                    pass
        entry.update(
            iteration=trainer.updater.iteration,
            epoch=trainer.updater.epoch,
            elapsed_time=trainer.elapsed_time,
        )
        self.log.append(entry)
        self._accum, self._count = {}, 0
        path = os.path.join(trainer.out, self._filename)
        with open(path, "w") as f:
            json.dump(self.log, f, indent=1, default=float)


class PrintReport:
    def __init__(self, keys, log_report: Optional[LogReport] = None):
        self.trigger = (1, "epoch")
        self.priority = 40
        self._keys = keys
        self._log_report = log_report

    def __call__(self, trainer):
        src = (self._log_report.log[-1]
               if self._log_report and self._log_report.log
               else {**trainer.observation,
                     "iteration": trainer.updater.iteration,
                     "epoch": trainer.updater.epoch})
        parts = []
        for k in self._keys:
            v = src.get(k)
            parts.append(f"{k}={float(v):.6g}" if v is not None else f"{k}=--")
        print("  ".join(parts))
