"""Iterators — minimal Chainer-style batch iterators plus the multi-node
wrappers (reference: ``chainermn/iterators/``: ``create_multi_node_iterator``
master/slave bcast pairs, ``create_synchronized_iterator`` RNG sync;
unverified — mount empty, see SURVEY.md).

Since this framework stands alone (no Chainer), it ships its own
``SerialIterator`` implementing the protocol the reference assumed
(``next()``, ``epoch``, ``is_new_epoch``, ``epoch_detail``, ``reset()``).

Single-controller shift: the reference needed a master/slave pair because
each rank was a separate process that might draw different batches; the
master ran the real iterator and MPI-broadcast every batch.  With one
controller feeding all devices, identical-batch semantics are free.  In
multi-process mode the same guarantee comes from *seed agreement*
(synchronized shuffling) instead of shipping batches — the broadcast
variant exists for iterators that are genuinely non-deterministic
(e.g. streaming sources only the master can see).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from chainermn_tpu.iterators.prefetch import (
    DeviceWindow,
    PrefetchIterator,
    StagingConverter,
)

__all__ = [
    "DeviceWindow",
    "PrefetchIterator",
    "SerialIterator",
    "StagingConverter",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
]


def _array_columns(dataset):
    """Fast-path detection: a numpy-array dataset (rows = examples), or
    a TUPLE of numpy field arrays sharing their leading dim (a list of
    arrays stays on the generic path — lists hold examples, tuples hold
    columns, the same rule ``default_converter`` applies to batches).
    Returns the column tuple or None (generic per-element path)."""
    if isinstance(dataset, np.ndarray):
        return (dataset,)
    if isinstance(dataset, tuple) and dataset and all(
            isinstance(a, np.ndarray) and a.ndim >= 1 for a in dataset):
        n = len(dataset[0])
        if all(len(a) == n for a in dataset):
            return tuple(dataset)
    return None


class SerialIterator:
    """Sequential batch iterator with epoch bookkeeping.

    Generic datasets (anything indexable) yield LISTS of examples, the
    Chainer protocol.  Numpy-array datasets — one array (rows =
    examples) or a tuple of field arrays sharing their leading dim —
    take a fancy-indexing fast path: the batch is gathered with ONE
    ``dataset[order[start:stop]]`` per field instead of a per-element
    Python loop, and yielded already stacked (an ``np.ndarray``, or a
    tuple of them) — ``default_converter`` passes such batches through
    without re-stacking.
    """

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = False, seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self._repeat = repeat
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        self.reset()

    @property
    def dataset_length(self) -> int:
        """Number of examples (≠ ``len(dataset)`` for tuple-of-field-
        arrays datasets, where that counts fields)."""
        return self._len

    def reset(self):
        # re-derive from self.dataset: callers may swap the dataset
        # attribute and reset() (the resize-on-resume pattern)
        self._columns = _array_columns(self.dataset)
        self._len = (len(self._columns[0]) if self._columns is not None
                     else len(self.dataset))
        self.epoch = 0
        self.is_new_epoch = False
        self._pos = 0
        self._exhausted = False
        self._order = np.arange(self._len)
        if self._shuffle:
            self._rng.shuffle(self._order)

    @property
    def repeat(self) -> bool:
        return self._repeat

    @property
    def epoch_detail(self) -> float:
        return self.epoch + self._pos / max(self._len, 1)

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        n = self._len
        start = self._pos
        stop = min(start + self.batch_size, n)
        if self._columns is not None:
            idx = self._order[start:stop]
            cols = tuple(a[idx] for a in self._columns)
            batch = cols[0] if isinstance(self.dataset, np.ndarray) \
                else cols
        else:
            batch = [self.dataset[int(i)]
                     for i in self._order[start:stop]]
        self._pos = stop
        if self._pos >= n:
            # epoch completes WITH this batch (Chainer contract: ``epoch``
            # counts finished sweeps at the moment the closing batch is
            # returned, so epoch-triggered extensions see the right value)
            self.epoch += 1
            self.is_new_epoch = True
            self._pos = 0
            if self._repeat:
                if self._shuffle:
                    self._rng.shuffle(self._order)
            else:
                self._exhausted = True
        else:
            self.is_new_epoch = False
        return batch

    next = __next__

    # -- resume protocol (reference: Chainer serialized the iterator into
    # the trainer snapshot, so a resumed run continues mid-epoch with the
    # same shuffle order instead of silently restarting the epoch) ------ #

    def state_dict(self) -> dict:
        s = self._rng.get_state()
        return {
            "epoch": self.epoch,
            "is_new_epoch": self.is_new_epoch,
            "pos": self._pos,
            "exhausted": self._exhausted,
            "order": np.asarray(self._order).copy(),
            "rng_keys": np.asarray(s[1], np.uint32),
            "rng_pos": int(s[2]),
            "rng_has_gauss": int(s[3]),
            "rng_cached": float(s[4]),
        }

    def load_state_dict(self, st: dict) -> None:
        self.epoch = int(st["epoch"])
        self.is_new_epoch = bool(st["is_new_epoch"])
        self._pos = int(st["pos"])
        self._exhausted = bool(st["exhausted"])
        self._order = np.asarray(st["order"])
        self._rng.set_state((
            "MT19937", np.asarray(st["rng_keys"], np.uint32),
            int(st["rng_pos"]), int(st["rng_has_gauss"]),
            float(st["rng_cached"])))


class _BroadcastIterator:
    """Wraps a master iterator; every process yields the master's batches.

    Multi-process: master materialises the batch and ``bcast_obj``s it; with
    a single controller the wrap is a transparent passthrough.
    """

    def __init__(self, iterator, comm, rank_master: int = 0):
        self._it = iterator
        self._comm = comm
        self._master = rank_master

    def __iter__(self):
        return self

    def __next__(self):
        comm, master = self._comm, self._master
        if comm.inter_size == 1:
            return next(self._it)
        if comm.inter_rank == master:
            try:
                batch = next(self._it)
                payload = ("batch", batch,
                           self._it.epoch, self._it.is_new_epoch)
            except StopIteration:
                payload = ("stop", None, None, None)
            payload = comm.bcast_obj(payload, root=master)
        else:
            payload = comm.bcast_obj(None, root=master)
        kind, batch, epoch, new_epoch = payload
        if kind == "stop":
            raise StopIteration
        self.epoch = epoch
        self.is_new_epoch = new_epoch
        return batch

    next = __next__

    def __getattr__(self, name):
        return getattr(self._it, name)

    def reset(self):
        self._it.reset()


def create_multi_node_iterator(iterator, comm, rank_master: int = 0):
    """Identical batches on every process (model-parallel requirement).

    Reference parity: ``chainermn.iterators.create_multi_node_iterator``
    (master runs the real iterator, slaves receive each batch via bcast).
    """
    return _BroadcastIterator(iterator, comm, rank_master)


def create_synchronized_iterator(iterator, comm, seed: int = 0):
    """Synchronise the iterator's RNG across processes so shuffle order
    matches (reference: ``create_synchronized_iterator``).

    The agreed seed is broadcast from process 0 and reseeds the iterator's
    RNG — afterwards every process draws identical shuffle permutations
    without any per-batch communication (cheaper than the broadcast
    iterator; this was the reference's point too).
    """
    agreed = comm.bcast_obj(seed, root=0)
    if hasattr(iterator, "_rng"):
        iterator._rng = np.random.RandomState(agreed)
        iterator.reset()
    return iterator
