"""Overlapped input pipeline: prefetching host→device feed.

The reference closed the input-pipeline gap on GPUs with
``MultiprocessIterator`` workers plus pure_nccl's double-buffer threads
(SURVEY §3.1); the single-controller JAX port reopened it — every
``StandardUpdater.update()`` paid iterator pull → convert → ``np.stack``
→ ``jax.device_put`` → dispatch in series, with the devices idle during
host assembly.  This module is the TPU-native answer: a bounded
slot-ring (depth-k) background worker that pulls, converts, stacks the
NEXT fused window and issues its ``jax.device_put`` onto the mesh
sharding *ahead of consumption*, so steady-state step time is
``max(host, device)`` instead of ``host + device``.

Three layers, lowest first:

- :func:`default_converter` / :class:`StagingConverter` — batch → tuple
  of stacked host arrays.  The staging variant stacks each column
  directly into a small ring of preallocated buffers reused across
  steps when shapes repeat (no per-element ``np.asarray`` copy, no
  per-step allocation).
- :func:`apply_batch_policy` — the world-size divisibility policy
  (drop-remainder or raise), shared verbatim with the synchronous
  updater path so both feeds are bitwise-identical.
- :class:`PrefetchIterator` — the slot-ring worker.  Yields
  :class:`DeviceWindow` records (device-resident, sharding-placed
  fused windows) instead of raw batches; propagates worker exceptions
  on ``next()``; shuts down cleanly; and implements
  ``state_dict``/``load_state_dict`` by draining in-flight slots and
  rewinding the base iterator to the oldest unconsumed pull, so
  checkpoint semantics match the serial path exactly.

``utils.comm_model.choose_prefetch_depth`` picks the slot count from
the measured host-assembly / device-step ratio; ``docs/PIPELINE.md``
explains when overlap helps.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from typing import Callable, Optional

import numpy as np

from chainermn_tpu.utils.telemetry import get_recorder

__all__ = [
    "DeviceWindow",
    "PrefetchIterator",
    "StagingConverter",
    "apply_batch_policy",
    "assemble_window",
    "default_converter",
    "put_window",
]


def default_converter(batch):
    """Batch → tuple of stacked host arrays (Chainer's concat_examples).

    Accepts three batch shapes:

    - ``list`` of examples (the generic iterator protocol): each example
      a scalar/array (→ one stacked column) or a tuple/list of fields
      (→ one stacked column per field).  ``np.stack`` coerces elements
      itself — no per-element ``np.asarray`` pre-pass (that was a second
      copy for non-ndarray examples).
    - ``np.ndarray``: an already-stacked batch (the
      :class:`~chainermn_tpu.SerialIterator` numpy fast path) — passed
      through as a single column, zero copies.
    - ``tuple`` whose elements are ALL ``np.ndarray``: already-stacked
      per-field columns (fast-path tuple datasets,
      :class:`NativeBatchIterator`) — passed through.  A tuple holding
      anything else (e.g. a tuple of example-tuples) is a batch of
      examples and stacks like a list.
    """
    if not len(batch):
        raise ValueError("empty batch")
    if isinstance(batch, np.ndarray):
        return (batch,)
    if isinstance(batch, tuple) and all(
            isinstance(col, np.ndarray) for col in batch):
        # all-ndarray tuple = pre-stacked columns; any other tuple is a
        # batch of examples (e.g. a tuple of example-tuples) and takes
        # the stacking path below, as it always did
        return batch
    first = batch[0]
    if isinstance(first, (tuple, list)):
        cols = list(zip(*batch))
        return tuple(np.stack(col) for col in cols)
    return (np.stack(batch),)


class StagingConverter:
    """:func:`default_converter` minus the per-step allocation.

    Stacks each column directly into a preallocated staging buffer
    (``np.stack(col, out=buf)``) reused across steps when the column's
    (length, element shape, dtype) repeat — steady-state training hits
    the same shapes every step, so after warmup batch assembly is one
    memcpy into a recycled buffer instead of allocate + copy.

    Buffers rotate through a ring of ``n_buffers`` per column so the
    last ``n_buffers - 1`` returned batches stay valid while in flight
    (a fused window holds up to ``steps_per_execution + 1`` unstacked
    batches during assembly, and ``jax.device_put`` may still be
    reading single-step batches under async dispatch / prefetch).
    Size the ring ≥ ``max(depth, steps_per_execution + 1) + 3``;
    :class:`PrefetchIterator`'s default converter does this.

    Already-stacked array batches (fast-path iterators) pass through
    untouched, same as :func:`default_converter`.
    """

    def __init__(self, n_buffers: int = 4):
        if n_buffers < 2:
            raise ValueError("need at least 2 staging buffers "
                             "(one filling, one in flight)")
        self._n_buffers = n_buffers
        self._rings: dict = {}      # key -> [buffers...]
        self._turn: dict = {}       # key -> next ring index

    def _staging(self, key, shape, dtype):
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = []
            self._turn[key] = 0
        i = self._turn[key]
        if len(ring) <= i:
            ring.append(np.empty(shape, dtype))
        self._turn[key] = (i + 1) % self._n_buffers
        return ring[i]

    def owns_buffers(self, arrays) -> bool:
        """True if any of ``arrays`` IS one of this converter's ring
        buffers (will be overwritten on ring wrap-around).  The feed
        uses this to force such transfers to completion before the
        buffer can be recycled — see :func:`put_window`."""
        bufs = {id(b) for ring in self._rings.values() for b in ring}
        return any(id(a) in bufs for a in arrays)

    def _stack(self, col_idx, col):
        first = col[0]
        if isinstance(first, np.ndarray) and all(
                isinstance(v, np.ndarray)
                and v.shape == first.shape and v.dtype == first.dtype
                for v in col):
            key = (col_idx, len(col), first.shape, first.dtype)
            buf = self._staging(key, (len(col),) + first.shape,
                                first.dtype)
            return np.stack(col, out=buf)
        # mixed / non-array elements (python scalars, ragged): let numpy
        # decide the result dtype exactly as default_converter would
        return np.stack(col)

    def __call__(self, batch):
        if not len(batch):
            raise ValueError("empty batch")
        if isinstance(batch, np.ndarray):
            return (batch,)
        if isinstance(batch, tuple) and all(
                isinstance(col, np.ndarray) for col in batch):
            return batch
        first = batch[0]
        if isinstance(first, (tuple, list)):
            cols = list(zip(*batch))
            return tuple(self._stack(i, col) for i, col in enumerate(cols))
        return (self._stack(0, batch),)


def apply_batch_policy(arrays, world_size: int, drop_remainder: bool):
    """World-size divisibility policy, shared by the serial and
    prefetched feeds (identical batches → bitwise-identical training)."""
    if arrays[0].shape[0] % world_size:
        if not drop_remainder:
            raise ValueError(
                f"global batch {arrays[0].shape[0]} not divisible by "
                f"world size {world_size}")
        keep = (arrays[0].shape[0] // world_size) * world_size
        if keep == 0:
            raise ValueError(
                f"batch of {arrays[0].shape[0]} examples cannot be "
                f"sharded over {world_size} devices — raise batch_size "
                f"to at least the world size")
        arrays = tuple(a[:keep] for a in arrays)
    return arrays


def assemble_window(pull_fn, n_steps: int):
    """THE window-fill contract, shared by the serial updater feed and
    the prefetch worker (one definition → the prefetch-on/off bitwise
    parity cannot drift): fill up to ``n_steps`` same-shape batches
    from ``pull_fn``; stop early on iterator exhaustion or a ragged
    (end-of-epoch partial) batch, which can't stack — the ragged batch
    rides along as the pending tail.  Returns ``(window, pending)``;
    the FIRST pull's StopIteration propagates."""
    first = pull_fn()
    window, pending = [first], None
    while len(window) < n_steps:
        try:
            nxt = pull_fn()
        except StopIteration:
            break
        if any(a.shape != b.shape for a, b in zip(nxt, first)):
            pending = nxt
            break
        window.append(nxt)
    return window, pending


def put_window(window, pending, batch_sharding, stacked_sharding,
               converter=None, source=None):
    """Transfer an assembled window: single batches go up under the
    per-example sharding, multi-step windows are stacked with the
    leading scan axis unsharded.  Returns ``(arrays, k, tail)`` —
    shared by both feeds, like :func:`assemble_window`.

    Aliasing hazard: sharded ``device_put`` of a host array can DEFER
    the per-shard copy until first use, silently aliasing the source —
    and ``block_until_ready`` does NOT force it (the alias counts as
    ready; measured on the CPU backend).  Harmless for arrays nobody
    mutates (fast-path fancy-index gathers, fresh ``np.stack``
    outputs), fatal for a converter's recycled staging buffer — the
    ring wraps and rewrites a window already handed downstream — the
    same goes for an iterator recycling its own output buffers
    (:class:`NativeBatchIterator` slot views).  When ``converter`` or
    ``source`` (the batch iterator) advertises its buffers
    (``owns_buffers``, see :class:`StagingConverter`), those arrays are
    COPIED before the transfer — the one copy the direct-to-device path
    fundamentally owes; staging still wins for fused windows, whose
    window-level stack is the copy.  A custom converter or iterator
    that reuses memory without advertising it must copy itself."""
    import jax

    probes = [p for p in (getattr(converter, "owns_buffers", None),
                          getattr(source, "owns_buffers", None))
              if p is not None]

    def _safe(arrays):
        if not probes:
            return arrays
        return tuple(
            np.array(a) if any(p((a,)) for p in probes) else a
            for a in arrays)

    k = len(window)
    if k == 1:
        arrays = tuple(jax.device_put(a, batch_sharding)
                       for a in _safe(window[0]))
    else:
        # the window-level np.stack already copies out of any staging
        # buffers, so the stacked transfer can stay fully lazy
        arrays = tuple(
            jax.device_put(np.stack(cols), stacked_sharding)
            for cols in zip(*window))
    tail = None if pending is None else tuple(
        jax.device_put(a, batch_sharding) for a in _safe(pending))
    return arrays, k, tail


class DeviceWindow:
    """One prefetched fused window, already on device.

    ``arrays``: tuple of device arrays — sharded ``(batch, ...)`` when
    ``k == 1``, ``(k, batch/k-per-step, ...)`` stacked windows (leading
    scan axis unsharded) when ``k > 1``.  ``tail``: the ragged
    end-of-epoch batch that could not stack into the window (device
    arrays, single-step sharding), or None.  The epoch bookkeeping is
    the base iterator's state AFTER the window's final pull — what the
    serial path would observe at the same consumption point.
    """

    __slots__ = ("arrays", "k", "tail", "epoch", "is_new_epoch",
                 "epoch_detail")

    def __init__(self, arrays, k, tail, epoch, is_new_epoch,
                 epoch_detail):
        self.arrays = arrays
        self.k = k
        self.tail = tail
        self.epoch = epoch
        self.is_new_epoch = is_new_epoch
        self.epoch_detail = epoch_detail

    @property
    def n_iterations(self) -> int:
        """Training iterations this window advances (k + ragged tail)."""
        return self.k + (1 if self.tail is not None else 0)


class PrefetchIterator:
    """Bounded slot-ring prefetcher: background host assembly + ahead-of-
    consumption ``jax.device_put``.

    Wraps a batch iterator (``SerialIterator`` protocol) and yields
    :class:`DeviceWindow` records: the next ``steps_per_execution``-deep
    fused window, converted, stacked, divisibility-policed, and ALREADY
    transferred onto the communicator's mesh sharding — all done by a
    daemon worker thread up to ``depth`` windows ahead of the consumer.

    Semantics contract (pinned by ``tests/iterator_tests/test_prefetch``):

    - the window/tail stream is identical to what ``StandardUpdater``'s
      serial path assembles (same converter → same policy → same
      stacking), so training with prefetch on vs off is bitwise equal;
    - a worker exception is re-raised from ``next()`` (not swallowed in
      a background thread, the reference MultiprocessIterator's classic
      failure mode);
    - ``close()`` joins the worker — no leaked threads;
    - ``state_dict()`` drains in-flight slots and rewinds the base
      iterator to the oldest UNCONSUMED pull before snapshotting, so a
      checkpoint resumes exactly where the consumer stood, not where
      the read-ahead had raced to.  The discarded lookahead is re-pulled
      after the rewind (the restored RNG makes the replay identical).

    Args:
      iterator: base batch iterator (``next``/``epoch``/``epoch_detail``;
        ``state_dict``/``load_state_dict`` required only for resume).
      comm: communicator supplying ``mesh``/``axis_name``/``size`` for
        sharding placement and the divisibility policy.
      converter: batch → tuple of host arrays; default a
        :class:`StagingConverter` with ``depth + 3`` buffers.
      steps_per_execution: fused window size — the updater wires its
        FULL dispatch window here, ``steps_per_execution ×
        accum_steps`` when gradient accumulation is on (the feed is
        agnostic to how the window splits into optimiser updates).
      depth: slot-ring length — windows prefetched ahead.  See
        ``utils.comm_model.choose_prefetch_depth``.
      drop_remainder: the divisibility policy switch.
      join_timeout: seconds ``state_dict``/``reset``/``close`` wait for
        the worker to stop.  A base iterator blocked inside ``next()``
        (streaming source with no data) cannot observe the stop flag;
        after the timeout ``state_dict``/``reset`` raise instead of
        hanging the trainer, and ``close`` warns and abandons the
        daemon worker (it exits on its own once the pull unblocks).
    """

    def __init__(self, iterator, comm, converter: Optional[Callable] = None,
                 steps_per_execution: int = 1, depth: int = 2,
                 drop_remainder: bool = True, join_timeout: float = 60.0):
        import jax  # deferred: keep module import light
        from jax.sharding import NamedSharding, PartitionSpec as P

        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        if steps_per_execution < 1:
            raise ValueError("steps_per_execution must be >= 1")
        self._base = iterator
        self._comm = comm
        # ring sizing: during window assembly up to steps_per_execution
        # + 1 (pending) converted batches are live BEFORE the window
        # stack copies them, on top of the depth + inflight single-step
        # windows whose staging buffers device_put may still be reading
        self._converter = converter if converter is not None else \
            StagingConverter(
                n_buffers=max(depth, steps_per_execution + 1) + 3)
        if isinstance(self._converter, StagingConverter) and \
                self._converter._n_buffers < steps_per_execution + 1:
            # an undersized ring recycles buffers still referenced IN
            # the unstacked window — duplicated batches, no error
            raise ValueError(
                f"StagingConverter(n_buffers="
                f"{self._converter._n_buffers}) is too small for "
                f"steps_per_execution={steps_per_execution}: the ring "
                f"must hold the whole unstacked window "
                f"(>= steps_per_execution + 1 buffers)")
        self._n_steps = steps_per_execution
        self.depth = depth
        self._drop_remainder = drop_remainder
        self.join_timeout = join_timeout
        self._batch_sharding = NamedSharding(comm.mesh, P(comm.axis_name))
        self._stacked_sharding = NamedSharding(
            comm.mesh, P(None, comm.axis_name))
        self._can_rewind = (hasattr(iterator, "state_dict")
                            and hasattr(iterator, "load_state_dict"))

        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._buffer: list = []        # drained-but-unconsumed items
        self._spill: list = []         # worker's undelivered item on halt
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._finished = False

        self.epoch = getattr(iterator, "epoch", 0)
        self.is_new_epoch = getattr(iterator, "is_new_epoch", False)
        self._epoch_detail = float(getattr(iterator, "epoch_detail", 0.0))

    # ------------------------------------------------------------------ #
    # worker side
    # ------------------------------------------------------------------ #

    def _snapshot(self):
        return self._base.state_dict() if self._can_rewind else None

    def _pull(self):
        arrays = self._converter(next(self._base))
        return apply_batch_policy(arrays, self._comm.size,
                                  self._drop_remainder)

    def _to_device(self, window, pending):
        arrays, k, tail = put_window(
            window, pending, self._batch_sharding, self._stacked_sharding,
            converter=self._converter, source=self._base)
        return DeviceWindow(
            arrays, k, tail,
            epoch=getattr(self._base, "epoch", 0),
            is_new_epoch=getattr(self._base, "is_new_epoch", False),
            epoch_detail=float(getattr(self._base, "epoch_detail", 0.0)))

    def _deliver(self, item) -> bool:
        """Put with stop-polling; on halt the item goes to the spill
        list instead of being dropped (its pre-pull snapshot is the
        rewind point when the consumer checkpoints mid-flight)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        self._spill.append(item)
        return False

    def _worker(self):
        try:
            while not self._stop.is_set():
                # re-resolved per window (like the consumer side): a
                # set_recorder() swap mid-run must not strand this
                # long-lived thread on the old recorder
                tracer = get_recorder()
                snap = self._snapshot()
                try:
                    with tracer.span("prefetch/assemble", cat="input"):
                        window, pending = assemble_window(
                            self._pull, self._n_steps)
                except StopIteration:
                    self._deliver(("stop", None, snap))
                    return
                with tracer.span("prefetch/put", cat="input",
                                 k=len(window)):
                    rec = self._to_device(window, pending)
                if not self._deliver(("window", rec, snap)):
                    return
        except BaseException as e:  # noqa: BLE001 — propagate on next()
            self._deliver(("error", e, None))

    # ------------------------------------------------------------------ #
    # consumer side
    # ------------------------------------------------------------------ #

    def _ensure_worker(self):
        if self._thread is None and not self._finished \
                and self._error is None:
            self._thread = threading.Thread(
                target=self._worker, name="PrefetchIterator-worker",
                daemon=True)
            self._thread.start()

    def _take(self):
        if self._buffer:
            return self._buffer.pop(0)
        while True:
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if self._thread is None or not self._thread.is_alive():
                    # the worker may have delivered its final item in
                    # the race window between our timeout and its exit —
                    # re-check the queue before declaring it dead
                    try:
                        return self._q.get_nowait()
                    except queue.Empty:
                        pass
                    if self._spill:
                        return self._spill.pop(0)
                    raise RuntimeError(
                        "prefetch worker exited without a result")

    def __iter__(self):
        return self

    def __next__(self) -> DeviceWindow:
        if self._error is not None:
            raise self._error
        if self._finished:
            raise StopIteration
        self._ensure_worker()
        tracer = get_recorder()
        with tracer.span("prefetch/slot_wait", cat="input"):
            kind, rec, _snap = self._take()
        # occupancy AFTER the take: ~depth when device-bound, ~0 when
        # host-bound — the docs/PIPELINE.md diagnostic as a Perfetto
        # counter track
        tracer.counter("prefetch/occupancy", self.buffered)
        if kind == "error":
            self._error = rec
            self._join()
            raise rec
        if kind == "stop":
            self._finished = True
            self._join()
            raise StopIteration
        self.epoch = rec.epoch
        self.is_new_epoch = rec.is_new_epoch
        self._epoch_detail = rec.epoch_detail
        return rec

    next = __next__

    @property
    def epoch_detail(self) -> float:
        """Consumed position (NOT the read-ahead position — the worker
        may have raced several windows past this)."""
        return self._epoch_detail

    @property
    def buffered(self) -> int:
        """Windows currently staged ahead of the consumer.  ~depth when
        the pipeline is device-bound (worker outruns the consumer), ~0
        when host-bound — the cheap live diagnostic for which side to
        optimise (``docs/PIPELINE.md``)."""
        return self._q.qsize() + len(self._buffer)

    @property
    def repeat(self) -> bool:
        return getattr(self._base, "repeat", True)

    # wrapper-owned attribute names: everything assigned in __init__ /
    # consumer bookkeeping.  Anything else reads AND writes through to
    # the base iterator, so the codebase's blessed mutate-then-reset
    # patterns (create_synchronized_iterator's ``it._rng = ...``, the
    # resize-on-resume ``it.dataset = new; it.reset()``) keep working
    # through the wrapper instead of landing on it and silently
    # diverging from the base.
    _OWN_ATTRS = frozenset((
        "_base", "_comm", "_converter", "_n_steps", "depth",
        "_drop_remainder", "_batch_sharding", "_stacked_sharding",
        "_can_rewind", "_q", "_buffer", "_spill", "_stop", "_thread",
        "_error", "_finished", "epoch", "is_new_epoch", "_epoch_detail",
        "join_timeout",
    ))

    def __getattr__(self, name):
        # only fires for names not set on the wrapper — no recursion
        return getattr(self._base, name)

    def __setattr__(self, name, value):
        if name in self._OWN_ATTRS or "_base" not in self.__dict__ \
                or not hasattr(self._base, name):
            object.__setattr__(self, name, value)
        else:
            setattr(self._base, name, value)

    # ------------------------------------------------------------------ #
    # shutdown / halt
    # ------------------------------------------------------------------ #

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _halt(self):
        """Stop the worker and collect everything it produced, in order:
        drained queue items first (older), then the spilled in-flight
        item (newer).  Leaves the iterator restartable.  Raises
        RuntimeError after ``join_timeout`` if the worker never stops —
        a base iterator blocked inside ``next()`` can't see the stop
        flag, and hanging the caller (a checkpoint extension, shutdown)
        would be strictly worse than failing loudly."""
        if self._thread is None:
            return
        self._stop.set()
        deadline = time.monotonic() + self.join_timeout
        while self._thread.is_alive():
            try:
                self._buffer.append(self._q.get(timeout=0.05))
            except queue.Empty:
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"prefetch worker did not stop within "
                    f"{self.join_timeout}s — the base iterator's "
                    f"next() appears to be blocked (streaming source "
                    f"with no data?); raise join_timeout or unblock "
                    f"the source before checkpointing")
        self._thread.join()
        self._thread = None
        while True:
            try:
                self._buffer.append(self._q.get_nowait())
            except queue.Empty:
                break
        self._buffer.extend(self._spill)
        self._spill = []
        self._stop = threading.Event()

    def close(self):
        """Join the worker and drop buffered lookahead.  Idempotent; the
        iterator restarts its worker on the next ``next()`` (after a
        rewindable base is restored, the replay is identical).  A worker
        stuck in a blocked ``next(base)`` is abandoned with a warning
        rather than hanging shutdown — it is a daemon and exits once
        the pull unblocks (the set stop flag is the first thing it
        sees)."""
        try:
            self._halt()
        except RuntimeError as e:
            warnings.warn(f"PrefetchIterator.close: {e}", RuntimeWarning)
            return
        if self._can_rewind and self._buffer:
            # don't strand the lookahead: rewind so a later next() (or a
            # plain consumer of the base iterator) sees the unconsumed
            # batches again
            self._rewind_to(self._oldest_snapshot())
        self._buffer = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # pragma: no cover — belt and braces
        try:
            self._stop.set()
        except Exception:
            pass

    # ------------------------------------------------------------------ #
    # resume protocol
    # ------------------------------------------------------------------ #

    def _oldest_snapshot(self):
        """Base-iterator state as of the oldest UNCONSUMED pull.  An
        error sentinel at the head carries no snapshot (the failed pull
        never completed) — keep the exception sticky instead of losing
        it with the drained buffer, and fall back to the live base
        state (the stream is broken at exactly this point anyway)."""
        for kind, rec, snap in self._buffer:
            if kind == "error":
                self._error = rec
                return self._snapshot()
            return snap
        return self._snapshot()

    def _rewind_to(self, st):
        if st is None:
            return
        # deep-copy arrays: load_state_dict may alias them (SerialIterator
        # keeps the order array and shuffles it in place) and the caller
        # holds this dict as the checkpoint payload
        self._base.load_state_dict({
            k: (v.copy() if isinstance(v, np.ndarray) else v)
            for k, v in st.items()})

    def state_dict(self) -> dict:
        """Drain in-flight slots, rewind the base iterator to the
        consumer's position, and return ITS state — exactly the dict the
        serial path would have produced at this consumption point, so
        a snapshot taken under prefetch restores into either path."""
        if not self._can_rewind:
            # no rewind protocol: the snapshot can't be exact, but the
            # CURRENT run must not lose the already-pulled lookahead —
            # keep it buffered (``_take`` serves the buffer first)
            self._halt()
            return {"non_resumable": True}
        self._halt()
        st = self._oldest_snapshot()
        self._rewind_to(st)          # discard lookahead; worker replays
        self._buffer = []
        self._finished = False       # the replayed stream re-derives it
        return st

    def load_state_dict(self, st: dict) -> None:
        self._halt()
        self._buffer = []
        self._error = None
        self._finished = False
        if st and not st.get("non_resumable") and self._can_rewind:
            self._rewind_to(st)
        self.epoch = getattr(self._base, "epoch", 0)
        self.is_new_epoch = getattr(self._base, "is_new_epoch", False)
        self._epoch_detail = float(
            getattr(self._base, "epoch_detail", 0.0))

    def reset(self):
        self._halt()
        self._buffer = []
        self._error = None
        self._finished = False
        self._base.reset()
        self.epoch = getattr(self._base, "epoch", 0)
        self.is_new_epoch = getattr(self._base, "is_new_epoch", False)
        self._epoch_detail = float(
            getattr(self._base, "epoch_detail", 0.0))
