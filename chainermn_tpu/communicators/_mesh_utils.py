"""Mesh construction & rank-topology math.

TPU-native replacement for ChainerMN's ``_communication_utility.py``
(``init_ranks`` discovered intra/inter ranks by allgathering hostnames over
MPI; ``init_nccl_comm`` broadcast NCCL unique ids).  On TPU none of that
exists: the JAX runtime already knows the device topology, so "rank
discovery" is reading ``jax.devices()`` / ``jax.process_index()``, and there
is no NCCL communicator to initialise — XLA lowers collectives onto ICI/DCN
from the mesh itself.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def world_devices(devices: Optional[Sequence] = None):
    """Flat list of devices forming the world, in global-rank order."""
    if devices is None:
        devices = jax.devices()
    return sorted(devices, key=lambda d: d.id)


def make_world_mesh(
    devices: Optional[Sequence] = None, axis_name: str = "world"
) -> Mesh:
    """1-D mesh over all devices — the flat world every communicator wraps."""
    devs = world_devices(devices)
    return Mesh(np.asarray(devs, dtype=object), (axis_name,))


def make_named_mesh(
    axis_sizes: dict,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """N-D mesh from ``{axis_name: size}`` (insertion order = major→minor).

    Axes should be ordered so that the *fastest-communicating* axis (tensor/
    sequence parallel) is minor — adjacent device ids sit on the same ICI
    link/host, so minor-axis collectives ride ICI while major axes (data,
    pipeline) may cross DCN.  A size of -1 means "whatever is left".
    """
    devs = world_devices(devices)
    sizes = dict(axis_sizes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([v for v in sizes.values() if v != -1]))
    if unknown:
        if len(devs) % known:
            raise ValueError(
                f"{len(devs)} devices not divisible by fixed axes {sizes}"
            )
        sizes[unknown[0]] = len(devs) // known
    total = int(np.prod(list(sizes.values())))
    if total != len(devs):
        raise ValueError(f"mesh {sizes} needs {total} devices, have {len(devs)}")
    arr = np.asarray(devs, dtype=object).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def intra_rank(process_index: Optional[int] = None) -> int:
    """Local device index contract (ChainerMN used intra_rank to pick the GPU;
    on TPU the runtime pins devices, so this is informational)."""
    return 0  # single-controller: the controller's "first local device"


def topology() -> dict:
    """Describe the world: device/process counts and per-process spans."""
    return {
        "num_devices": jax.device_count(),
        "num_local_devices": jax.local_device_count(),
        "num_processes": jax.process_count(),
        "process_index": jax.process_index(),
    }
