"""Communicator protocol — the TPU-native analogue of ChainerMN's
``CommunicatorBase`` (reference: ``chainermn/communicators/communicator_base.py``,
unverified — reference mount empty; see SURVEY.md caveat).

Design note (TPU-first, not a port)
-----------------------------------
ChainerMN's communicator is an *eager, per-process* object: every rank is a
separate OS process holding its own arrays, and each collective is a blocking
MPI/NCCL call. JAX on TPU is a *single-controller SPMD* world: one Python
process (per host) drives N devices, arrays are sharded over a
:class:`jax.sharding.Mesh`, and collectives are XLA ops (``psum``,
``all_gather``, ``all_to_all``, ``ppermute``) traced inside ``jit``.

So this communicator has two faces:

1. **In-program (hot path)** — ``comm.axis_name`` names the mesh axis; the
   differentiable functional collectives in :mod:`chainermn_tpu.ops` take that
   axis name and are used *inside* jitted step functions. This is where
   gradient allreduce actually happens (XLA lowers it onto ICI).

2. **Eager/host path (control plane)** — methods on this class. Array
   collectives operate on *world-stacked* arrays: an array whose leading axis
   has length ``size`` and is sharded one-slice-per-rank over the mesh
   (the SPMD analogue of "each rank holds its local array"). Object
   (``*_obj``) collectives move picklable Python values between *processes*
   (hosts); with a single controller they are host-local and cheap.

Rank model (per SURVEY.md §5): ``rank``/``size`` index the flat world of
devices participating in the mesh axis; ``process_rank`` ↔
``jax.process_index()``; ``intra_rank`` ↔ local device index (the reference's
device-placement contract, ``chainermn`` used it to pick the GPU).
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Sequence


class CommunicatorBase(abc.ABC):
    """Abstract communicator with ChainerMN's full collective/p2p surface.

    All array collectives use the *world-stacked* convention: an argument
    ``x`` with shape ``(size, ...)`` represents "rank ``i`` holds ``x[i]``",
    sharded over the mesh axis.  Methods return world-stacked results so that
    they compose; use :meth:`local` to pull out one rank's slice.
    """

    # ------------------------------------------------------------------ #
    # topology
    # ------------------------------------------------------------------ #

    @property
    @abc.abstractmethod
    def size(self) -> int:
        """Number of ranks (devices) in this communicator's world."""

    @property
    @abc.abstractmethod
    def rank(self) -> int:
        """This controller's rank for object/control-plane purposes.

        In multi-host mode this is the first global device index owned by
        this process; in single-controller mode it is 0.  Per-device identity
        inside jitted code comes from ``lax.axis_index(comm.axis_name)``.
        """

    @property
    @abc.abstractmethod
    def intra_rank(self) -> int:
        """Local (within-host) device index — device placement contract."""

    @property
    @abc.abstractmethod
    def inter_rank(self) -> int:
        """Host index (``jax.process_index()``)."""

    @property
    @abc.abstractmethod
    def inter_size(self) -> int:
        """Number of hosts (``jax.process_count()``)."""

    @property
    @abc.abstractmethod
    def axis_name(self) -> str:
        """Mesh axis name for in-jit collectives over this world."""

    @property
    @abc.abstractmethod
    def mesh(self):
        """The :class:`jax.sharding.Mesh` backing this communicator."""

    @abc.abstractmethod
    def split(self, color: int, key: int) -> "CommunicatorBase":
        """New communicator over the subset of ranks sharing ``color``,
        ranked by ``key`` (MPI_Comm_split semantics)."""

    # ------------------------------------------------------------------ #
    # world-stacked array collectives (eager control plane)
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def bcast(self, x, root: int = 0):
        """Every rank gets ``x[root]``. Returns world-stacked ``(size, ...)``."""

    @abc.abstractmethod
    def allreduce(self, x, op: str = "sum"):
        """Elementwise reduce ``x[0..size)`` with ``op``; every rank gets it."""

    @abc.abstractmethod
    def allgather(self, x):
        """Every rank gets the full stack: ``(size, size, ...)``."""

    @abc.abstractmethod
    def alltoall(self, x):
        """Rank i's j-th slice goes to rank j's i-th slice (transpose of the
        leading two world axes). ``x`` is ``(size, size, ...)``."""

    @abc.abstractmethod
    def gather(self, x, root: int = 0):
        """Root gets the stack ``(size, ...)`` (SPMD: computed everywhere)."""

    @abc.abstractmethod
    def scatter(self, x, root: int = 0):
        """Rank i gets ``x[root][i]``; ``x`` is world-stacked ``(size, size, ...)``."""

    @abc.abstractmethod
    def reduce_scatter(self, x):
        """Rank i gets ``sum_j x[j, i]``; ``x`` is ``(size, size, ...)``."""

    @abc.abstractmethod
    def send(self, x, dest: int, source: int):
        """Point-to-point move of ``x[source]`` into slot ``dest`` (ppermute)."""

    # ------------------------------------------------------------------ #
    # object (host/control) collectives
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def bcast_obj(self, obj: Any, root: int = 0) -> Any: ...

    @abc.abstractmethod
    def gather_obj(self, obj: Any, root: int = 0) -> Optional[Sequence[Any]]: ...

    @abc.abstractmethod
    def allgather_obj(self, obj: Any) -> Sequence[Any]: ...

    @abc.abstractmethod
    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any: ...

    @abc.abstractmethod
    def scatter_obj(self, objs: Optional[Sequence[Any]], root: int = 0) -> Any: ...

    @abc.abstractmethod
    def alltoall_obj(self, objs: Sequence[Any]) -> Sequence[Any]:
        """Per-process object exchange: ``objs[j]`` is delivered to the
        communicator's j-th member process; returns the objects received
        from every member (same order).  Control-plane only — the data
        plane belongs in :func:`chainermn_tpu.ops.alltoall`.

        Contract: all ``*_obj`` collectives share ONE member order —
        ascending process index — so ``allgather_obj`` row ``j`` and
        ``alltoall_obj`` slot ``j`` always refer to the same process
        (``shuffle_data_blocks`` and topology discovery rely on this).
        """
        ...

    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int) -> None: ...

    @abc.abstractmethod
    def recv_obj(self, source: int) -> Any: ...

    @abc.abstractmethod
    def barrier(self) -> None: ...

    # ------------------------------------------------------------------ #
    # model/training helpers (ChainerMN parity:
    # bcast_data / multi_node_mean_grad on pytrees)
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def bcast_data(self, params, root: int = 0):
        """Broadcast a pytree of arrays from ``root`` so every rank/device
        holds identical values (ChainerMN's first-``update()`` weight sync)."""

    @abc.abstractmethod
    def multi_node_mean_grad(self, grads, dtype=None, fused: bool = True,
                             bucket_bytes=None, plan=None):
        """Mean a world-stacked pytree of gradients across ranks.

        ``dtype`` mirrors ``allreduce_grad_dtype``: cast before the reduce
        (e.g. ``jnp.bfloat16``) and back after — the TPU analogue of
        ChainerMN's fp16 allreduce.

        ``fused`` (default) packs the whole pytree into flat
        dtype-grouped buckets of ``bucket_bytes`` and issues one
        collective per bucket (:func:`chainermn_tpu.ops.fused_allreduce`)
        instead of one per leaf; backends whose world spans multiple
        hosts (``inter_size > 1``) additionally lower each bucket
        hierarchically (reduce-scatter intra → all-reduce inter →
        all-gather intra).  ``fused=False`` keeps the per-leaf path.

        ``plan`` supersedes the per-call kwargs with a MEASURED
        exchange plan (``utils/autotune.py``): a
        :class:`~chainermn_tpu.utils.autotune.Plan` (or its dict form)
        executes directly; ``"auto"`` consults the persistent plan
        cache for this (topology, payload) signature and tunes on a
        miss — rank 0's winner is broadcast so every process compiles
        the identical program.
        """

    # alias, ChainerMN kept both names
    def allreduce_grad(self, grads, dtype=None, fused: bool = True,
                       bucket_bytes=None, plan=None):
        return self.multi_node_mean_grad(grads, dtype, fused=fused,
                                         bucket_bytes=bucket_bytes,
                                         plan=plan)

    # ------------------------------------------------------------------ #
    # conveniences
    # ------------------------------------------------------------------ #

    def local(self, x, rank: Optional[int] = None):
        """Pull rank ``rank``'s slice out of a world-stacked array."""
        import jax

        r = self.rank if rank is None else rank
        return jax.tree.map(lambda a: a[r], x)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"<{type(self).__name__} size={self.size} rank={self.rank} "
            f"axis={self.axis_name!r}>"
        )
