"""Communicator factory — analogue of ``chainermn.create_communicator``
(reference: ``chainermn/communicators/__init__.py``, unverified — mount
empty, see SURVEY.md).

ChainerMN shipped seven communicators that were all *allreduce algorithm
variants* over MPI/NCCL (naive, flat, hierarchical, two_dimensional,
single_node, non_cuda_aware, pure_nccl).  On TPU the algorithm choice is
XLA's job — it picks ring/tree/bidirectional schedules per mesh axis over
ICI/DCN — so those seven collapse into one ``tpu_xla`` backend plus a
``loopback`` for single-rank runs.  The legacy names are accepted as
aliases (with the mapping logged) so reference users can port launch
scripts unchanged.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

from ._obj_channel import DataSizeError
from .base import CommunicatorBase
from .loopback import LoopbackCommunicator
from .tpu_xla import TpuXlaCommunicator

_LEGACY_ALIASES = {
    # ChainerMN name      -> TPU-native behaviour
    "naive": "tpu_xla",
    "flat": "tpu_xla",
    "hierarchical": "tpu_xla",
    "two_dimensional": "tpu_xla",
    "single_node": "tpu_xla",
    "non_cuda_aware": "tpu_xla",
    "pure_nccl": "tpu_xla",
}


def create_communicator(
    communicator_name: str = "tpu_xla",
    devices: Optional[Sequence] = None,
    axis_name: str = "world",
    allreduce_grad_dtype=None,
    batched_copy: bool = True,  # accepted for parity; XLA always fuses
) -> CommunicatorBase:
    """Create a communicator.

    Args:
      communicator_name: ``"tpu_xla"`` (all devices, XLA collectives over
        ICI/DCN), ``"loopback"`` (size-1), or a legacy ChainerMN name
        (mapped to ``tpu_xla`` with a warning).
      devices: optional explicit device list (default: all ``jax.devices()``).
      axis_name: mesh axis name used for in-jit collectives.
      allreduce_grad_dtype: cast gradients to this dtype around the mean
        (ChainerMN's fp16 allreduce; use ``jnp.bfloat16`` on TPU).
      batched_copy: ignored — XLA fuses pack/cast/reduce automatically.
    """
    name = communicator_name
    if name in _LEGACY_ALIASES:
        warnings.warn(
            f"communicator {name!r} is a ChainerMN legacy alias; using "
            f"{_LEGACY_ALIASES[name]!r} (XLA chooses the collective "
            "algorithm per mesh axis)",
            stacklevel=2,
        )
        name = _LEGACY_ALIASES[name]

    if name == "loopback":
        dev = devices[0] if devices else None
        return LoopbackCommunicator(device=dev, axis_name=axis_name)
    if name == "tpu_xla":
        return TpuXlaCommunicator(
            devices=devices, axis_name=axis_name,
            grad_dtype=allreduce_grad_dtype,
        )
    raise ValueError(
        f"unknown communicator {communicator_name!r}; "
        f"choose from ['tpu_xla', 'loopback'] or legacy "
        f"{sorted(_LEGACY_ALIASES)}"
    )


__all__ = [
    "CommunicatorBase",
    "DataSizeError",
    "LoopbackCommunicator",
    "TpuXlaCommunicator",
    "create_communicator",
]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Initialise the JAX multi-host runtime — the ``mpiexec -n N`` moment.

    ChainerMN's process model was MPI launch: one rank per GPU, world size
    fixed by ``mpiexec``.  The TPU-native model is one *process per host*
    (each driving its local chips), wired together by the JAX distributed
    runtime.  On Cloud TPU pods all arguments are auto-detected from the
    environment; elsewhere pass them explicitly — they correspond 1:1 to
    MPI's (coordinator ≈ rank-0 endpoint, num_processes ≈ world size,
    process_id ≈ rank).

    Call once per process BEFORE any other JAX API, then
    ``create_communicator("tpu_xla")`` sees the global device set
    (``comm.size`` = all chips in the pod, ``comm.inter_size`` = hosts).

    No-ops gracefully when the runtime is already initialised (so single-
    host runs and tests can call it unconditionally).
    """
    import jax

    # Idempotence: jax.distributed.initialize raises if called twice, and
    # its message wording varies by version — test the runtime state, not
    # the error string.  The state probes live in jax._src, so guard them:
    # if a future JAX moves them, fall back to calling initialize and
    # swallowing only the single-host "too late / again" RuntimeErrors.
    probes_ok = True
    try:
        from jax._src import distributed, xla_bridge

        if distributed.global_state.client is not None:
            return
        backend_up = xla_bridge.backends_are_initialized()
    except Exception:
        probes_ok = False
        backend_up = False
    # Single-host convenience: with no explicit cluster spec there is
    # nothing to coordinate, and jax.distributed.initialize would raise if
    # the XLA backend is already up — let unconditional calls in tests and
    # single-process runs fall through to a no-op in that case.
    single_host = num_processes in (None, 1) and coordinator_address is None
    if single_host and backend_up:
        return

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError:
        if probes_ok or not single_host:
            raise
        # probes unavailable on this JAX version and this is a single-host
        # call: a RuntimeError here means "already initialized" or
        # "backend already up", both of which are the documented no-op case


__all__.append("init_distributed")
