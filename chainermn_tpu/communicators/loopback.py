"""``loopback`` communicator — single-rank world for tests and single-device
runs.  The fake the reference never had (SURVEY.md §4): ChainerMN tests
required a real ``mpiexec -n 2``; here a size-1 communicator makes every
collective an identity/copy so the full training stack runs unmodified on
one chip (or CPU) with zero communication.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .base import CommunicatorBase


class LoopbackCommunicator(CommunicatorBase):
    def __init__(self, device=None, axis_name: str = "world"):
        self._device = device or jax.devices()[0]
        self._axis = axis_name
        self._mesh = Mesh(np.asarray([self._device], dtype=object), (axis_name,))
        self._queue: list = []

    size = property(lambda self: 1)
    rank = property(lambda self: 0)
    intra_rank = property(lambda self: 0)
    inter_rank = property(lambda self: 0)
    inter_size = property(lambda self: 1)
    axis_name = property(lambda self: self._axis)
    mesh = property(lambda self: self._mesh)

    def split(self, color: int, key: int) -> "LoopbackCommunicator":
        return self

    # world-stacked arrays have leading dim 1; all collectives are identity
    def _chk(self, x):
        x = jnp.asarray(x)
        if x.shape[:1] != (1,):
            raise ValueError(f"world-stacked leading dim must be 1, got {x.shape}")
        return x

    def bcast(self, x, root: int = 0):
        return self._chk(x)

    def allreduce(self, x, op: str = "sum"):
        return self._chk(x)

    def allgather(self, x):
        return self._chk(x)[None]

    def alltoall(self, x):
        return self._chk(x)

    def gather(self, x, root: int = 0):
        return self.allgather(x)

    def scatter(self, x, root: int = 0):
        return self._chk(x)[:, 0]

    def reduce_scatter(self, x):
        return self._chk(x)[:, 0]

    def send(self, x, dest: int, source: int):
        return self._chk(x)

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        return obj

    def gather_obj(self, obj: Any, root: int = 0):
        return [obj]

    def allgather_obj(self, obj: Any) -> Sequence[Any]:
        return [obj]

    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any:
        return obj

    def scatter_obj(self, objs: Optional[Sequence[Any]], root: int = 0) -> Any:
        return objs[0] if objs else None

    def alltoall_obj(self, objs: Sequence[Any]) -> Sequence[Any]:
        if len(objs) != 1:
            raise ValueError(
                f"alltoall_obj expects 1 send object at size 1, got "
                f"{len(objs)}")
        # round-trip through pickle to keep loopback faithful to transport
        return [pickle.loads(pickle.dumps(o)) for o in objs]

    def send_obj(self, obj: Any, dest: int) -> None:
        # round-trip through pickle to keep loopback faithful to transport
        self._queue.append(pickle.dumps(obj))

    def recv_obj(self, source: int) -> Any:
        if not self._queue:
            raise RuntimeError("recv_obj: empty mailbox")
        return pickle.loads(self._queue.pop(0))

    def barrier(self) -> None:
        pass

    def bcast_data(self, params, root: int = 0):
        # jnp.copy: donation-safe, see TpuXlaCommunicator.bcast_data
        return jax.tree.map(
            lambda a: jnp.copy(jax.device_put(jnp.asarray(a), self._device)),
            params)

    def multi_node_mean_grad(self, grads, dtype=None, fused=True,
                             bucket_bytes=None, plan=None):
        # size-1 world: fused, planned or not, the mean is the identity
        return jax.tree.map(self._chk, grads)
