"""``tpu_xla`` communicator — the flagship backend (ChainerMN's ``pure_nccl``
analogue; reference: ``chainermn/communicators/pure_nccl_communicator.py``,
unverified — mount empty, see SURVEY.md).

Everything ChainerMN did with NCCL ring allreduce on CUDA streams, this does
by *letting XLA lower mesh collectives onto ICI*: there is no hand-written
ring, no stream management, no pack/unpack arena — ``lax.psum`` over a mesh
axis compiles to the TPU's native reduction over the torus, fused with
neighbouring computation.  The eager methods below wrap those same XLA
collectives in ``jax.jit(shard_map(...))`` so host-driven code (datasets,
checkpoint agreement, tests) can use them on *world-stacked* arrays
(leading axis = rank, sharded over the mesh).

fp16/bf16 gradient reduction (``allreduce_grad_dtype``) maps to a cast
around ``pmean`` — XLA fuses the casts into the collective's neighbourhood,
which is the TPU equivalent of ChainerMN's fused divide+cast CuPy kernels.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _mesh_utils
from ._obj_channel import KVObjectChannel
from .base import CommunicatorBase

_REDUCE_OPS = ("sum", "mean", "max", "min", "prod")

# Chunk size for multi-host *_obj collectives: payloads stream through the
# process-spanning runtime in frames instead of one monolithic buffer
# (ChainerMN chunked MPI messages under the 2**31-byte count limit; here
# the limit is host memory for the gather staging buffers).
_OBJ_FRAME_BYTES = 64 * 1024 * 1024

# per-process creation count of communicators with the same member-device
# identity — disambiguates the KV namespace of re-created communicators
# (SPMD-consistent: every process creates the same communicators in order)
_INCARNATIONS: dict = {}


class TpuXlaCommunicator(CommunicatorBase):
    """Collectives over a 1-D device mesh, lowered by XLA onto ICI/DCN."""

    def __init__(
        self,
        devices: Optional[Sequence] = None,
        axis_name: str = "world",
        grad_dtype=None,
    ):
        self._devices = _mesh_utils.world_devices(devices)
        self._axis = axis_name
        self._mesh = Mesh(np.asarray(self._devices, dtype=object), (axis_name,))
        self._grad_dtype = grad_dtype
        self._obj_queues: dict = {}  # same-process p2p object mailbox
        # KV namespace must (a) be identical on every process creating the
        # logically-same communicator and (b) differ between distinct
        # communicators (split() children renumber ranks from 0, so key
        # collisions with the parent would cross-deliver messages).  The
        # member device-id set gives (b) across *concurrent* communicators;
        # a per-ident incarnation counter gives it across *re-created* ones
        # (a second split() with the same members would otherwise restart
        # its sequence numbers on the first incarnation's still-live keys).
        # The counter is SPMD-consistent because every process constructs
        # the same communicators in the same order — the program-identity
        # discipline the whole framework already assumes.
        import hashlib

        ident = hashlib.md5(
            ",".join(str(d.id) for d in self._devices).encode()
        ).hexdigest()[:10]
        inc = _INCARNATIONS.get(ident, 0)
        _INCARNATIONS[ident] = inc + 1
        self._obj_channel = KVObjectChannel(
            tag=f"cmnobj-{axis_name}-{ident}-i{inc}")
        self._jit_cache: dict = {}  # per-instance (avoids lru_cache self leak)
        # processes owning member devices, sorted: the obj-collective
        # roster.  A split() child spanning fewer than all processes must
        # NOT use the whole-world multihost collectives (non-members never
        # enter the call -> deadlock) — it rides the KV group path.
        self._member_procs = sorted(
            {d.process_index for d in self._devices})

    # -- topology ------------------------------------------------------ #

    @property
    def size(self) -> int:
        return len(self._devices)

    @property
    def rank(self) -> int:
        # first global rank owned by this process (0 in single-controller)
        for i, d in enumerate(self._devices):
            if d.process_index == jax.process_index():
                return i
        return 0

    @property
    def intra_rank(self) -> int:
        """Index of this controller's rank device among this host's local
        devices — the reference's device-placement contract (ChainerMN used
        ``intra_rank`` to pick the local GPU, so it must be a LOCAL index,
        never a global device id)."""
        own = self._devices[self.rank]
        for i, d in enumerate(jax.local_devices()):
            if d.id == own.id:
                return i
        return 0

    @property
    def inter_rank(self) -> int:
        return jax.process_index()

    @property
    def inter_size(self) -> int:
        return jax.process_count()

    @property
    def axis_name(self) -> str:
        return self._axis

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def split(self, color: int, key: int) -> "TpuXlaCommunicator":
        """MPI_Comm_split analogue over the device world.

        Single-controller SPMD twist: the controller knows every rank's
        (color, key) is the same function of rank it computed locally, so a
        split is just selecting the device subset for ``color`` — no
        communication needed (the reference allgathered (color, key) pairs).
        Callers pass per-rank colors/keys via vectors of length ``size``.
        """
        color = np.asarray(color)
        key = np.asarray(key)
        if color.ndim == 0 or key.ndim == 0:
            raise ValueError(
                "single-controller split needs per-rank vectors: MPI's "
                "per-process `split(color, key)` call sites become one call "
                "with length-`size` arrays here, e.g. "
                "split(np.arange(comm.size) % 2, np.arange(comm.size))")
        colors = np.broadcast_to(color, (self.size,))
        keys = np.broadcast_to(key, (self.size,))
        mine = colors[self.rank]
        members = [i for i in range(self.size) if colors[i] == mine]
        members.sort(key=lambda i: (keys[i], i))
        return TpuXlaCommunicator(
            [self._devices[i] for i in members],
            axis_name=self._axis,
            grad_dtype=self._grad_dtype,
        )

    # -- eager collective machinery ------------------------------------ #

    def _spec(self, *rest) -> NamedSharding:
        return NamedSharding(self._mesh, P(self._axis, *rest))

    def _stacked(self, x):
        """Device-put a world-stacked array with rank-sharded leading axis."""
        x = jnp.asarray(x)
        if x.shape[:1] != (self.size,):
            raise ValueError(
                f"world-stacked array must have leading dim {self.size}, "
                f"got shape {x.shape}"
            )
        return jax.device_put(x, self._spec())

    def _smap(self, fn):
        return jax.jit(
            jax.shard_map(
                fn, mesh=self._mesh,
                in_specs=P(self._axis), out_specs=P(self._axis),
            )
        )

    def _jitted(self, name: str):
        """Build & cache the jitted shard_map for collective ``name``.

        Cached per instance (not ``lru_cache``: a class-level cache would pin
        every communicator + its compiled executables alive forever).
        """
        key = ("plain", name)
        if key in self._jit_cache:
            return self._jit_cache[key]
        ax = self._axis

        if name in ("sum", "mean", "max", "min"):
            red = {"sum": lax.psum, "mean": lax.pmean,
                   "max": lax.pmax, "min": lax.pmin}[name]
            fn = self._smap(lambda s: red(s, ax))
        elif name == "prod":
            fn = self._smap(
                lambda s: jnp.prod(
                    lax.all_gather(s, ax, axis=0, tiled=True), axis=0,
                    keepdims=True)
            )
        elif name == "allgather":
            fn = self._smap(
                lambda s: lax.all_gather(s, ax, axis=0, tiled=True)[None])
        elif name == "alltoall":
            fn = self._smap(
                lambda s: lax.all_to_all(s, ax, split_axis=1, concat_axis=1))
        elif name == "reduce_scatter":
            # local in: (1, size, ...) -> strip world dim, scatter over dim 0
            # -> local out (1, ...) which re-stacks to (size, ...): rank i
            # gets sum_j x[j, i] (ChainerMN exposed this inside pure_nccl only)
            fn = self._smap(
                lambda s: lax.psum_scatter(
                    s[0], ax, scatter_dimension=0, tiled=True))
        else:
            raise KeyError(name)
        self._jit_cache[key] = fn
        return fn

    def _jitted_root(self, name: str, root: int):
        key = (name, root)
        if key in self._jit_cache:
            return self._jit_cache[key]
        ax = self._axis

        if name == "bcast":
            def _bcast(s):
                idx = lax.axis_index(ax)
                return lax.psum(jnp.where(idx == root, s, jnp.zeros_like(s)), ax)
            fn = self._smap(_bcast)
        elif name == "scatter":
            def _scatter(s):
                idx = lax.axis_index(ax)
                full = lax.psum(jnp.where(idx == root, s, jnp.zeros_like(s)), ax)
                piece = lax.dynamic_index_in_dim(full[0], idx, axis=0,
                                                 keepdims=False)
                return piece[None]
            fn = self._smap(_scatter)
        else:
            raise KeyError(name)
        self._jit_cache[key] = fn
        return fn

    def _jitted_perm(self, perm: tuple):
        key = ("perm", perm)
        if key in self._jit_cache:
            return self._jit_cache[key]
        ax = self._axis
        fn = self._smap(lambda s: lax.ppermute(s, ax, perm=list(perm)))
        self._jit_cache[key] = fn
        return fn

    # -- world-stacked array collectives -------------------------------- #

    def bcast(self, x, root: int = 0):
        return self._jitted_root("bcast", root)(self._stacked(x))

    def allreduce(self, x, op: str = "sum"):
        if op not in _REDUCE_OPS:
            raise ValueError(f"op must be one of {_REDUCE_OPS}")
        return self._jitted(op)(self._stacked(x))

    def allgather(self, x):
        return self._jitted("allgather")(self._stacked(x))

    def alltoall(self, x):
        x = self._stacked(x)
        if x.ndim < 2 or x.shape[1] != self.size:
            raise ValueError(
                f"alltoall needs (size, size, ...) input, got {x.shape}")
        return self._jitted("alltoall")(x)

    def gather(self, x, root: int = 0):
        # SPMD: gather == allgather computed everywhere; root is advisory.
        return self.allgather(x)

    def scatter(self, x, root: int = 0):
        x = self._stacked(x)
        if x.ndim < 2 or x.shape[1] != self.size:
            raise ValueError(
                f"scatter needs (size, size, ...) input, got {x.shape}")
        return self._jitted_root("scatter", root)(x)

    def reduce_scatter(self, x):
        x = self._stacked(x)
        if x.ndim < 2 or x.shape[1] != self.size:
            raise ValueError(
                f"reduce_scatter needs (size, size, ...) input, got {x.shape}")
        return self._jitted("reduce_scatter")(x)

    def send(self, x, dest: int, source: int):
        return self._jitted_perm(((source, dest),))(self._stacked(x))

    # -- object collectives (process/control plane) ---------------------- #
    #
    # With one controller per host, object transport is a *process*-level
    # concern (ChainerMN: pickled MPI messages).  Single process → local;
    # multi-process → pickle to uint8 arrays moved over the process-spanning
    # runtime.  ``root`` is a DEVICE rank (consistent with the array API);
    # it resolves to the process owning that device.

    def _root_process(self, root: int) -> int:
        return self._devices[root].process_index

    @property
    def _obj_local(self) -> bool:
        """True when this communicator's devices live in one process —
        obj collectives are then identities."""
        return jax.process_count() == 1 or len(self._member_procs) == 1

    @property
    def _obj_subgroup(self) -> bool:
        """True when members span >1 but not ALL processes (split child):
        obj collectives must scope to the member roster."""
        return 1 < len(self._member_procs) < jax.process_count()

    def _my_group_index(self) -> int:
        return self._member_procs.index(jax.process_index())

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        if self._obj_local:
            return obj
        if self._obj_subgroup:
            # only the root's payload matters: non-roots contribute None
            # so the KV store carries ONE copy (and a non-root's large
            # local object can't trip the size cap, matching the
            # whole-world path's source-only pickling)
            root_proc = self._root_process(root)
            objs = self._obj_channel.allgather(
                obj if jax.process_index() == root_proc else None,
                self._member_procs, jax.process_index())
            return objs[self._member_procs.index(root_proc)]
        from jax.experimental import multihost_utils

        is_src = self.inter_rank == self._root_process(root)
        payload = pickle.dumps(obj) if is_src else b""
        # length-prefix exchange, then frame-by-frame broadcast: the wire
        # never carries more than _OBJ_FRAME_BYTES at once
        n = int(multihost_utils.broadcast_one_to_all(
            np.asarray(len(payload), dtype=np.int64), is_source=is_src))
        out = bytearray()
        for off in range(0, n, _OBJ_FRAME_BYTES):
            ln = min(_OBJ_FRAME_BYTES, n - off)
            buf = np.zeros(ln, dtype=np.uint8)
            if is_src:
                buf[:] = np.frombuffer(payload[off : off + ln], dtype=np.uint8)
            out += np.asarray(multihost_utils.broadcast_one_to_all(
                buf, is_source=is_src)).tobytes()
        return pickle.loads(bytes(out))

    def allgather_obj(self, obj: Any) -> Sequence[Any]:
        if self._obj_local:
            return [obj]
        if self._obj_subgroup:
            return self._obj_channel.allgather(
                obj, self._member_procs, jax.process_index())
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj)
        lens = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(payload)], dtype=np.int64))).reshape(-1)
        n_max = int(lens.max())
        bufs = [bytearray() for _ in lens]
        # frame-by-frame gather, every process padded to the global frame
        # length so the collective stays SPMD-identical
        for off in range(0, n_max, _OBJ_FRAME_BYTES):
            ln = min(_OBJ_FRAME_BYTES, n_max - off)
            mine = np.zeros(ln, dtype=np.uint8)
            chunk = payload[off : off + ln]
            if chunk:
                mine[: len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
            rows = np.asarray(multihost_utils.process_allgather(mine))
            for p in range(len(lens)):
                bufs[p] += rows[p].tobytes()
        return [pickle.loads(bytes(bufs[p][: int(lens[p])]))
                for p in range(len(lens))]

    def gather_obj(self, obj: Any, root: int = 0):
        objs = self.allgather_obj(obj)
        # ChainerMN contract: only root's process receives the list (lets
        # ported code use ``gather_obj(x) is not None`` as a root check).
        return objs if self.inter_rank == self._root_process(root) else None

    def allreduce_obj(self, obj: Any, op: str = "sum") -> Any:
        objs = self.allgather_obj(obj)
        return _tree_reduce(objs, op)

    def scatter_obj(self, objs, root: int = 0) -> Any:
        if self._obj_local:
            return objs[0] if objs else None
        all_lists = self.bcast_obj(objs, root)  # root = device rank
        return all_lists[self._my_group_index()]

    def alltoall_obj(self, objs, window: int = 8) -> Sequence[Any]:
        """Per-process object exchange over PAIRWISE p2p lanes.

        Staggered rounds (offset d: send to me+d, recv from me−d) with
        up to ``window`` sends published ahead of the blocking recvs,
        and a group barrier after every ``window`` recv rounds.  The KV
        channel's ``send`` is a publish (no rendezvous), so the
        look-ahead overlaps this process's publish round-trips with its
        recv waits; the epoch barrier is what makes the footprint claim
        TRUE rather than optimistic — recv progress alone says nothing
        about whether one's *receivers* have consumed one's publishes
        (a skewed peer lets every other process race ahead and strand
        O(n) payloads on the coordination service).  After a barrier at
        round d, every payload for rounds ≤ d is provably consumed;
        since sends run ahead to round d+window−1 while the last fence
        only guarantees consumption through the previous multiple of
        window, the store holds at most ``2·window − 1`` of each
        process's payloads at any time — per-process memory and KV
        footprint stay O(window · payload + recv volume), never the
        whole exchange (the property ``shuffle_data_blocks`` relies on
        for datasets too large to gather anywhere).

        Latency is O(n) recv rounds with publish latency hidden inside
        the window and n/window barrier fences.  ``window=1``
        degenerates to strictly-alternating send/recv/fence rounds
        (the most conservative footprint)."""
        if window < 1:
            raise ValueError(f"window {window} must be >= 1")
        n = 1 if self._obj_local else len(self._member_procs)
        if len(objs) != n:
            raise ValueError(
                f"alltoall_obj expects {n} send objects (one per member "
                f"process), got {len(objs)}")
        if self._obj_local:
            # pickle round-trip keeps single-process behaviour faithful
            # to the real transport (unpicklables fail here, not on a pod)
            return [pickle.loads(pickle.dumps(o)) for o in objs]
        me = self._my_group_index()
        # object p2p addresses controllers: each member process's first
        # device rank
        ctrl = [self._controller_rank(p) for p in self._member_procs]
        out: list = [None] * n
        out[me] = pickle.loads(pickle.dumps(objs[me]))
        sent = 1                      # rounds whose send is published
        for d in range(1, n):
            while sent < n and sent - d < window:
                dst = (me + sent) % n
                self._obj_channel.send(objs[dst], src=self.rank,
                                       dst=ctrl[dst])
                sent += 1
            src = (me - d) % n
            out[src] = self._obj_channel.recv(src=ctrl[src],
                                              dst=self.rank)
            if d % window == 0 and d < n - 1:
                # epoch fence: every member has now completed rounds
                # <= d, so every payload published for them is consumed
                # and deleted — the store's per-process footprint is
                # re-bounded to the window regardless of peer skew
                self.barrier()
        return out

    def send_obj(self, obj: Any, dest: int) -> None:
        """Point-to-point object send to device rank ``dest``.

        Same-process destinations use a local mailbox; cross-process ones
        ride the coordination-service KV channel with MPI-ordered
        (src, dst, seq) message matching — the TPU-native replacement for
        ChainerMN's pickled MPI p2p messages.
        """
        if self._root_process(dest) == jax.process_index():
            # This controller plays every local rank, so the only real
            # same-process destination is itself (loopback mailbox).
            if dest != self.rank:
                raise ValueError(
                    f"send_obj: rank {dest} lives in this process — there "
                    f"is no peer process to deliver to (own rank "
                    f"{self.rank}); same-process object p2p only loops "
                    "back to self")
            self._obj_queues.setdefault(dest, []).append(obj)
            return
        self._check_controller_rank(dest, "send_obj dest")
        self._obj_channel.send(obj, src=self.rank, dst=dest)

    def _controller_rank(self, proc: int) -> int:
        """The device rank object p2p addresses for process ``proc``:
        its first-owned rank in the shared device order."""
        return next(i for i, d in enumerate(self._devices)
                    if d.process_index == proc)

    def _check_controller_rank(self, r: int, what: str) -> None:
        """Object p2p endpoints are *controllers* (one per process), not
        devices: the remote peer only ever receives as its own first-owned
        rank, so any other device rank would publish an unreceivable
        message."""
        proc = self._root_process(r)
        controller = self._controller_rank(proc)
        if r != controller:
            raise ValueError(
                f"{what}={r} is device rank {r} of process {proc}, but "
                f"object p2p addresses controllers: use rank {controller} "
                f"(that process's first device rank)")

    def recv_obj(self, source: int) -> Any:
        if self._root_process(source) == jax.process_index():
            q = self._obj_queues.get(self.rank, [])
            if not q:
                raise RuntimeError("recv_obj: empty mailbox")
            return q.pop(0)
        self._check_controller_rank(source, "recv_obj source")
        return self._obj_channel.recv(src=source, dst=self.rank)

    def barrier(self) -> None:
        if self._obj_local:
            return
        if self._obj_subgroup:
            self._obj_channel.allgather(
                None, self._member_procs, jax.process_index())
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"{self._axis}_barrier")

    # -- model/training helpers ----------------------------------------- #

    def bcast_data(self, params, root: int = 0):
        """Replicate a pytree across every device (first-update weight sync).

        On TPU the idiomatic form of ChainerMN's ``bcast_data(model)`` is
        "device_put with a fully-replicated sharding": XLA broadcasts from
        the source buffer over ICI.  In multi-host, processes must already
        hold identical host values (standard JAX same-program contract) or
        sync via :meth:`bcast_obj` first.
        """
        repl = NamedSharding(self._mesh, P())
        # jnp.copy: callers feed the result into donating jitted steps
        # (StandardUpdater) — device_put may alias the input buffer (even
        # with may_alias=False, observed on the CPU backend), and donation
        # would then delete the caller's original arrays out from under
        # them; an explicit copy guarantees an independent buffer
        return jax.tree.map(
            lambda a: jnp.copy(jax.device_put(jnp.asarray(a), repl)),
                            params)

    def multi_node_mean_grad(self, grads, dtype=None, fused=True,
                             bucket_bytes=None, plan=None):
        """Mean world-stacked grads across ranks (eager path, for tests and
        host-driven loops).  The hot path is :func:`chainermn_tpu.ops.pmean`
        inside the jitted train step — see optimizers.py.

        ``fused`` (default) compiles ONE program for the whole pytree
        that reduces dtype-grouped flat buckets — ceil(bytes/bucket)
        collectives instead of one per leaf — and, when this world spans
        multiple hosts with equal per-host device counts, lowers each
        bucket hierarchically over an (inter, intra) factorisation of
        the mesh so the cross-host stage moves 1/intra_size of the
        bytes.  ``fused=False`` keeps the historical per-leaf path.

        ``plan`` supersedes both: a tuned
        :class:`~chainermn_tpu.utils.autotune.Plan` (or dict) executes
        as compiled, and ``"auto"`` resolves one through the measured
        autotuner — persistent-cache warm start, live probe search on a
        miss, rank-0 decision broadcast over the object channel.
        """
        dtype = dtype or self._grad_dtype
        if plan is not None:
            return self._plan_mean(grads, plan)
        if fused:
            return self._fused_mean(grads, dtype, bucket_bytes)
        mean = self._jitted("mean")

        def one(g):
            g = self._stacked(g)
            if dtype is not None and g.dtype != dtype:
                return mean(g.astype(dtype)).astype(g.dtype)
            return mean(g)

        return jax.tree.map(one, grads)

    def _hier_factors(self):
        """(inter_axis_row_major device grid, intra size) when this
        world spans >1 host with equal per-host device counts — the
        layout the 2-stage bucket lowering reduces over; ``None`` when
        the world is flat (single host, or ragged ownership)."""
        by_proc: dict = {}
        for d in self._devices:
            by_proc.setdefault(d.process_index, []).append(d)
        if len(by_proc) < 2:
            return None
        counts = {len(v) for v in by_proc.values()}
        if len(counts) != 1:
            return None
        rows = [by_proc[p] for p in sorted(by_proc)]
        return rows, counts.pop()

    def _fused_mean(self, grads, dtype, bucket_bytes):
        """One jitted shard_map over the whole grad pytree: fused
        bucketed mean, hierarchical when the world factors over hosts."""
        from chainermn_tpu.ops import fused as _fused

        bucket = bucket_bytes or _fused.DEFAULT_BUCKET_BYTES
        stacked = jax.tree.map(self._stacked, grads)
        leaves, treedef = jax.tree.flatten(stacked)
        key = ("fused_mean", str(dtype), bucket, treedef,
               tuple((l.shape, str(l.dtype)) for l in leaves))
        fn = self._jit_cache.get(key)
        if fn is None:
            ax = self._axis
            hier = self._hier_factors()
            if hier is not None:
                rows, intra = hier
                inter_ax = ax + "_inter"
                mesh = Mesh(np.asarray(rows, dtype=object), (inter_ax, ax))
                spec = P((inter_ax, ax))
                inter_kw = dict(inter_axis_name=inter_ax)
            else:
                mesh, spec, inter_kw = self._mesh, P(ax), {}

            def body(g):
                local = jax.tree.map(lambda a: a[0], g)
                red = _fused.fused_allreduce(
                    local, ax, op="mean", bucket_bytes=bucket,
                    wire_dtype=dtype, **inter_kw)
                return jax.tree.map(lambda a: a[None], red)

            fn = jax.jit(jax.shard_map(
                body, mesh=mesh, in_specs=spec, out_specs=spec))
            self._jit_cache[key] = fn
        return fn(stacked)

    def _plan_mean(self, grads, plan):
        """Plan-driven fused mean: one jitted shard_map whose strategy ×
        bucket × wire dtype come from a measured plan instead of
        defaults.  ``plan="auto"`` resolves through the autotuner
        (in-process memo → persistent cache → live probe search)."""
        from chainermn_tpu.utils import autotune as _autotune

        stacked = jax.tree.map(self._stacked, grads)
        leaves, treedef = jax.tree.flatten(stacked)
        shapes = tuple((l.shape, str(l.dtype)) for l in leaves)
        if isinstance(plan, str):
            if plan != "auto":
                raise ValueError(
                    f"plan={plan!r}: expected 'auto', a Plan, or a "
                    f"plan dict")
            # memo on the structural signature directly — no per-call
            # leaf slicing (a device gather each) or digest hashing;
            # the LOCAL tree is only materialised on the one tuning miss
            memo_key = ("plan_auto", treedef, shapes)
            plan = self._jit_cache.get(memo_key)
            if plan is None:
                local = jax.tree.map(lambda a: a[0], stacked)
                plan = _autotune.autotune_plan(self, local)
                self._jit_cache[memo_key] = plan
        else:
            plan = _autotune.Plan.from_any(plan)

        key = ("plan_mean", plan.strategy, plan.bucket_bytes,
               str(plan.wire_dtype), treedef, shapes)
        fn = self._jit_cache.get(key)
        if fn is None:
            ax = self._axis
            if plan.strategy == "hierarchical":
                mesh, inter_ax = _autotune._resolve_hier(
                    self, ax, None, None)
                if mesh is None:
                    raise ValueError(
                        "hierarchical plan on a world with no "
                        "(inter, intra) host factoring — the plan's "
                        "mesh signature does not match this "
                        "communicator")
            else:
                mesh, inter_ax = self._mesh, None
            # the stacked-exchange harness is autotune's probe builder
            # — ONE lowering shared by tuner, updater probe, and this
            # eager path
            fn = _autotune.build_exchange_fn(
                mesh, ax, plan.to_dict(), inter_axis_name=inter_ax)
            self._jit_cache[key] = fn
        return fn(stacked)


def _tree_reduce(objs, op: str):
    """Reduce a list of (possibly nested) scalar/dict/list objects."""
    import operator

    first = objs[0]
    if isinstance(first, dict):
        return {k: _tree_reduce([o[k] for o in objs], op) for k in first}
    if isinstance(first, (list, tuple)):
        t = type(first)
        return t(_tree_reduce([o[i] for o in objs], op)
                 for i in range(len(first)))
    if op == "sum":
        out = objs[0]
        for o in objs[1:]:
            out = operator.add(out, o)
        return out
    if op == "mean":
        return _tree_reduce(objs, "sum") / len(objs)
    if op == "max":
        return max(objs)
    if op == "min":
        return min(objs)
    raise ValueError(f"unsupported op {op!r} for object allreduce")
