"""Cross-process object p2p channel over the JAX coordination service.

ChainerMN's object transport was pickled MPI messages: a header
("msgtype": shapes/dtype) then raw chunks under the 2**31-byte MPI count
limit (reference: ``chainermn/communicators/mpi_communicator_base.py``,
unverified — mount empty, see SURVEY.md).  The TPU-native runtime has no
MPI; what every process *does* share is the JAX distributed
coordination service, whose key-value store accepts bytes.  This module
implements MPI-ordered p2p object send/recv on top of it:

- Message identity is ``(src_rank, dst_rank, seq)``; both ends keep a
  local per-peer sequence counter, so matching is deterministic exactly
  like MPI's per-(source, tag) message ordering — no header exchange.
- Payloads are chunked into KV-value frames (the service is gRPC-backed,
  so single values must stay well under the gRPC message ceiling).  The
  chunk keys are written first and the metadata key last, so a receiver
  blocked on the metadata key never observes a partial message.
- Keys are deleted after receipt, so the store does not grow with
  traffic.
- Transient coordination-service errors (connection reset, UNAVAILABLE)
  are absorbed by bounded exponential-backoff retries (``KV_RETRIES``);
  timeouts keep one-shot semantics and the per-lane sequence counters
  only advance after a message is known to exist, so a retried verb can
  never desynchronise the lane.  Retries feed the metrics registry
  (``comm/kv_retries`` counter, ``comm/kv_wait`` histogram) so a flaky
  coordination service is visible to a scraper, not just to whoever
  greps the logs.
- Every payload is tagged with the channel's **mesh generation**
  (:meth:`KVObjectChannel.set_generation` — the elastic-membership
  epoch).  A message published under an older generation — traffic from
  a pre-resize incarnation that survived on the store — is rejected at
  receipt with the typed :class:`StaleGenerationError` instead of being
  consumed as a live message by the resized world
  (``training/elastic.py``, docs/RESILIENCE.md "Elastic resume").

This is a *control-plane* channel (datasets, checkpoint agreement,
user-level ``send_obj``), not a tensor path — tensors ride XLA
collectives over ICI/DCN.
"""

from __future__ import annotations

import pickle
import time
from typing import Any


class StaleGenerationError(RuntimeError):
    """A received message was published under a different mesh
    generation than this channel's current one.  After an elastic
    resize, survivors fence their channels to the new membership epoch
    (:class:`chainermn_tpu.training.elastic.ElasticMembership`); a
    message from the pre-resize incarnation still sitting on the KV
    store must surface as this typed error, never be silently consumed
    as live traffic by the new world.  On the p2p lane the rejected
    message IS consumed (lane advanced, keys deleted — recv is the
    sole reader), so the lane stays usable for current-generation
    traffic; a group allgather rejects WITHOUT deleting (its n−1
    concurrent readers make deletion a race) and the whole collective
    must be re-entered together.

    Scope: fencing guards lanes WITHIN one coordination-service
    incarnation (channels whose both ends moved through the same epoch
    sequence).  Isolation between store incarnations comes from fresh
    channel tags (the communicators' incarnation counters) and, for
    between-run relaunches, from ``jax.distributed`` re-init handing
    every incarnation a fresh store."""


class DataSizeError(ValueError):
    """Raised when a single object exceeds the channel's hard size cap.

    ChainerMN raised ``DataSizeError`` when a scatter chunk exceeded the
    2**31-byte MPI count limit; this channel streams payloads in frames
    so the practical limit is much higher, but a hard cap still guards
    the coordination service from multi-GiB control messages (use the
    array collectives / dataset sharding for bulk data instead).
    """


# One KV value per frame; gRPC messages default to a low-MB ceiling, so
# stay comfortably below it.
FRAME_BYTES = 2 * 1024 * 1024
# Hard cap on a single p2p object (MPI-parity: 2**31).  Larger payloads
# should go through the chunked *_obj collectives or dataset sharding.
MAX_OBJ_BYTES = 2**31

# Bounded retry-with-exponential-backoff for TRANSIENT coordination-
# service failures (the service is gRPC-backed: a brief coordinator
# restart or connection reset must not kill a long training job mid-
# checkpoint-agreement).  Only errors matching these markers retry —
# a deadline/timeout expiry keeps its one-shot semantics (callers size
# timeout_ms for deadlock detection, retrying would silently multiply
# it), and anything unrecognised is a real bug that should surface.
KV_RETRIES = 4
KV_BACKOFF_BASE_S = 0.05
KV_BACKOFF_MAX_S = 2.0
_TRANSIENT_MARKERS = ("unavailable", "resource_exhausted", "socket closed",
                      "connection reset", "failed to connect",
                      "broken pipe", "goaway")


def _is_transient(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


def _kv_set(setter, key: str, value) -> None:
    """Retrying set that survives a first attempt which LANDED
    server-side before the transient error was reported: retried with
    ``allow_overwrite`` (same key, same value — idempotent), falling
    back to tolerating an already-exists rejection on clients whose
    signature predates the flag.

    Known bounded residue: if the first attempt landed AND the receiver
    consumed-and-deleted the key during the backoff window, the retry
    re-creates it and nothing deletes it again — a leaked key per such
    double-fault, not a correctness error (lane sequence counters only
    move forward, and communicator incarnations use fresh tags, so a
    resurrected key is never read as a live message by this channel
    instance).  Fixing it outright needs a compare-and-swap the
    coordination service does not expose."""
    def once():
        try:
            setter(key, value, allow_overwrite=True)
        except TypeError:
            try:
                setter(key, value)
            except Exception as e:
                if "already exists" in str(e).lower():
                    return
                raise

    _kv_retry(once, "key set")


def kv_overwrite(client, key: str, value) -> None:
    """ONE-attempt overwrite-in-place set — the shared primitive behind
    every periodically-republished status key (watchdog beats/metrics,
    membership records).  No retry/backoff: these run on hot or
    best-effort paths where a flaky service must cost one failed RPC,
    never sleeps — callers decide whether a failure is swallowed.  The
    legacy-client fallback is delete+set, NOT already-exists tolerance,
    which for an overwrite-in-place key would silently freeze the value
    (a frozen heartbeat counter reads as a dead peer)."""
    try:
        client.key_value_set(key, value, allow_overwrite=True)
    except TypeError:   # client predates allow_overwrite
        try:
            client.key_value_delete(key)
        except Exception:
            pass
        client.key_value_set(key, value)


def _kv_delete(client, key: str) -> None:
    """Retrying delete that also tolerates "already gone": a transient
    failure whose first attempt DID land server-side must not turn the
    retry into a spurious not-found error (lazy GC only needs the key
    absent)."""
    def once():
        try:
            client.key_value_delete(key)
        except Exception as e:
            if "not found" in str(e).lower():
                return
            raise

    _kv_retry(once, "key delete")


def _kv_retry(fn, what: str):
    """Call ``fn()`` retrying transient failures up to ``KV_RETRIES``
    times with exponential backoff; non-transient errors propagate
    immediately.  Safe for every KV verb used here: set/delete are
    idempotent (same key, same value / absent-ok), and a retried GET
    re-reads an immutable published value.

    This is the choke point every KV verb funnels through, so it is
    also where retries become observable: ``comm/kv_retries`` counts
    the retry attempts (0 on a clean first try — the counter moving at
    all means the coordination service is flaking) and ``comm/kv_wait``
    records each verb's total wall time including backoff sleeps.
    Disabled registry (the default) costs one attribute read."""
    from chainermn_tpu.utils.metrics import get_registry

    reg = get_registry()
    # t0 armed unconditionally: a registry enabled mid-verb must record
    # the verb's real duration, not perf_counter() minus a 0.0 sentinel
    t0 = time.perf_counter()

    def _observe(attempt: int) -> None:
        if not reg.enabled:
            return
        if attempt:
            reg.inc("comm/kv_retries", attempt)
        reg.observe("comm/kv_wait", time.perf_counter() - t0)

    delay = KV_BACKOFF_BASE_S
    for attempt in range(KV_RETRIES + 1):
        try:
            out = fn()
        except Exception as e:
            if attempt >= KV_RETRIES or not _is_transient(e):
                _observe(attempt)
                raise
            time.sleep(delay)
            delay = min(delay * 2, KV_BACKOFF_MAX_S)
        else:
            _observe(attempt)
            return out


# Envelope marker for generation-tagged payloads — self-describing so a
# mis-paired reader fails loudly instead of handing user code a tuple it
# never sent.
_GEN_ENVELOPE = "cmnobj-gen1"


class KVObjectChannel:
    """MPI-ordered object p2p between processes via the KV store."""

    def __init__(self, tag: str = "cmnobj", timeout_ms: int = 120_000):
        self._tag = tag
        self._timeout_ms = timeout_ms
        self._send_seq: dict = {}
        self._recv_seq: dict = {}
        self._ag_seq = 0
        self._ag_frames: dict = {}  # seq -> own frame count (for lazy GC)
        # mesh generation (elastic-membership epoch): every published
        # payload carries it, every received payload is checked against
        # it.  0 = the pre-elastic default; both ends of a lane move
        # together when ElasticMembership.fence() bumps it.
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def set_generation(self, generation: int) -> None:
        """Fence this channel to ``generation`` (the agreed membership
        epoch).  From now on published messages carry it and received
        messages must match it — see :class:`StaleGenerationError`."""
        self._generation = int(generation)

    @property
    def _client(self):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "KVObjectChannel needs the JAX distributed runtime; call "
                "chainermn_tpu.init_distributed(...) first")
        return client

    def _key(self, src: int, dst: int, seq: int, part: str) -> str:
        return f"{self._tag}/{src}.{dst}.{seq}/{part}"

    def _publish(self, obj: Any, keyfn, what: str) -> int:
        """Pickle + cap-check ``obj`` and write it as chunked frames with
        the metadata key last (its presence implies every chunk is
        readable).  ``keyfn(part)`` names the keys.  Returns the frame
        count."""
        payload = pickle.dumps((_GEN_ENVELOPE, self._generation, obj))
        if len(payload) > MAX_OBJ_BYTES:
            raise DataSizeError(
                f"{what} payload is {len(payload)} bytes, over the "
                f"{MAX_OBJ_BYTES}-byte cap; move bulk data through the "
                "array collectives or scatter_dataset instead")
        client = self._client
        nframes = max(1, -(-len(payload) // FRAME_BYTES))
        for k in range(nframes):
            _kv_set(client.key_value_set_bytes, keyfn(f"c{k}"),
                    payload[k * FRAME_BYTES : (k + 1) * FRAME_BYTES])
        _kv_set(client.key_value_set, keyfn("meta"),
                f"{nframes},{len(payload)}")
        return nframes

    def _collect(self, keyfn, what: str, meta: str = None) -> Any:
        """Blocking read of a message published by :meth:`_publish`.
        Pass ``meta`` when the caller already fetched the metadata key
        (recv's retry-safe existence check) to save a KV round-trip."""
        client = self._client
        if meta is None:
            meta = _kv_retry(lambda: client.blocking_key_value_get(
                keyfn("meta"), self._timeout_ms), f"{what} meta get")
        nframes, total = (int(v) for v in meta.split(","))
        buf = bytearray()
        for k in range(nframes):
            key = keyfn(f"c{k}")
            buf += _kv_retry(
                lambda key=key: client.blocking_key_value_get_bytes(
                    key, self._timeout_ms), f"{what} frame get")
        if len(buf) != total:
            raise RuntimeError(
                f"{what} corruption: expected {total} bytes, "
                f"reassembled {len(buf)}")
        msg = pickle.loads(bytes(buf))
        if not (isinstance(msg, tuple) and len(msg) == 3
                and msg[0] == _GEN_ENVELOPE):
            raise RuntimeError(
                f"{what}: payload is not a generation-tagged envelope — "
                "sender and receiver run different channel versions")
        gen, obj = msg[1], msg[2]
        if gen != self._generation:
            from chainermn_tpu.utils.metrics import get_registry

            get_registry().inc("comm/stale_generation_rejected")
            raise StaleGenerationError(
                f"{what}: message from mesh generation {gen} rejected "
                f"(this channel is fenced to generation "
                f"{self._generation}) — traffic from a different "
                "membership epoch must not be consumed as live")
        return obj

    def send(self, obj: Any, src: int, dst: int) -> None:
        """Send ``obj`` on the (src, dst) lane; returns when published."""
        seq = self._send_seq.get((src, dst), 0)
        self._send_seq[(src, dst)] = seq + 1
        self._publish(
            obj, lambda part: self._key(src, dst, seq, part), "send_obj")

    def allgather(self, obj: Any, group, me: int):
        """Group-scoped object allgather over the KV store.

        ``group``: sorted process ids participating; ``me`` must be one of
        them.  Returns the objects in ``group`` order.  This is the
        collective path for *subgroup* communicators (``split``), where
        the whole-world ``multihost_utils`` collectives would deadlock —
        non-member processes never enter the call.

        Key lifecycle (lazy GC): a process entering call ``s`` deletes its
        own keys from call ``s−2``.  Safe because reading call ``s−1``'s
        payloads — a precondition for any member reaching ``s`` — implies
        every member finished its ``s−2`` collect before publishing
        ``s−1``.
        """
        if me not in group:
            raise ValueError(f"process {me} not in group {sorted(group)}")
        client = self._client
        s = self._ag_seq
        self._ag_seq += 1
        old = self._ag_frames.pop(s - 2, None)
        if old is not None:
            for k in range(old):
                _kv_delete(client, self._key(me, -1, s - 2, f"gc{k}"))
            _kv_delete(client, self._key(me, -1, s - 2, "gmeta"))

        def keyfn(p):
            return lambda part: self._key(
                p, -1, s, "gmeta" if part == "meta" else "g" + part)

        self._ag_frames[s] = self._publish(obj, keyfn(me), "allgather_obj")
        # A stale-generation frame propagates _collect's typed error
        # WITHOUT deleting the rejected member's keys: unlike the p2p
        # lane (one reader — recv consumes what it rejects), a group
        # message has n−1 concurrent readers, and deleting under a peer
        # still mid-read would turn its fast typed rejection into a
        # full-timeout hang.  The orphaned keys are bounded by one
        # message and reclaimed by the publisher's lazy GC if it ever
        # allgathers again.
        return [
            obj if p == me else self._collect(
                keyfn(p), f"obj allgather from process {p}")
            for p in sorted(group)
        ]

    def recv(self, src: int, dst: int) -> Any:
        """Receive the next in-order object on the (src, dst) lane."""
        client = self._client
        seq = self._recv_seq.get((src, dst), 0)
        meta = _kv_retry(lambda: client.blocking_key_value_get(
            self._key(src, dst, seq, "meta"), self._timeout_ms),
            "obj channel meta get")
        # advance the lane only once the message is known to exist, so a
        # timed-out recv can be retried without desynchronising sequences
        # (the retry wrapper above only re-reads on TRANSIENT transport
        # errors — a timeout still propagates before this line runs)
        self._recv_seq[(src, dst)] = seq + 1
        nframes = int(meta.split(",")[0])

        def _delete_message():
            for k in range(nframes):
                _kv_delete(client, self._key(src, dst, seq, f"c{k}"))
            _kv_delete(client, self._key(src, dst, seq, "meta"))

        try:
            obj = self._collect(
                lambda part: self._key(src, dst, seq, part),
                "obj channel", meta=meta)
        except StaleGenerationError:
            # a rejected message is still CONSUMED: its keys are deleted
            # so the dead slot cannot shadow a later publish landing on
            # the same (src, dst, seq) coordinates
            _delete_message()
            raise
        _delete_message()
        return obj
