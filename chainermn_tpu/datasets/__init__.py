"""Dataset scattering across processes — analogue of ``chainermn.datasets``
(reference: ``chainermn/datasets/scatter_dataset.py``, ``empty_dataset.py``;
unverified — mount empty, see SURVEY.md).

Process model shift: ChainerMN scattered pickled sub-datasets from rank 0 to
every rank over MPI (one rank = one GPU).  On TPU one *process* feeds many
devices: datasets are scattered per-process (``jax.process_index()``), and
the per-process batch is then sharded across local devices inside the jitted
step.  With a single controller, "scattering" reduces to picking this
process's slice — no bytes move, which is itself the idiomatic design: every
process computes the same permutation from a shared seed instead of shipping
data through a root (the reference had to ship because ranks couldn't see
the dataset; TPU hosts usually mount the same storage).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .bpe import BPETokenizer, train_bpe  # noqa: F401  (re-export)

__all__ = [
    "scatter_dataset",
    "scatter_index",
    "create_empty_dataset",
    "shuffle_data_blocks",
    "SubDataset",
    "EmptyDataset",
    "BPETokenizer",
    "train_bpe",
]


class SubDataset:
    """A view of ``dataset`` through an index list (order = iteration order)."""

    def __init__(self, dataset, indices: np.ndarray):
        self._dataset = dataset
        self._indices = np.asarray(indices)

    def __len__(self) -> int:
        return len(self._indices)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self._indices[i]]
        return self._dataset[int(self._indices[i])]

    @property
    def indices(self) -> np.ndarray:
        return self._indices


def _partition(n: int, size: int, shuffle: bool, seed: Optional[int],
               force_equal_length: bool):
    order = np.arange(n)
    if shuffle:
        rng = np.random.RandomState(seed if seed is not None else 0)
        rng.shuffle(order)
    base = n // size
    rem = n % size
    parts = []
    start = 0
    for r in range(size):
        stop = start + base + (1 if r < rem else 0)
        parts.append(order[start:stop])
        start = stop
    if force_equal_length and rem:
        # pad short shards by wrapping (reference behaviour: equal-length
        # sub-datasets so every rank runs the same number of iterations —
        # SPMD requires identical step counts or collectives deadlock)
        target = base + 1
        parts = [
            p if len(p) == target else np.concatenate([p, order[: target - len(p)]])
            for p in parts
        ]
    return parts


def scatter_dataset(
    dataset,
    comm,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
):
    """Split ``dataset`` into near-equal shards, one per *process*.

    Every process derives the same partition from ``seed`` (deterministic
    SPMD agreement); only the metadata (length) is synchronised from root via
    ``bcast_obj`` so processes whose local dataset object is a stub still
    agree on the partition.
    """
    n = comm.bcast_obj(len(dataset), root=root)
    parts = _partition(n, comm.inter_size, shuffle, seed, force_equal_length)
    return SubDataset(dataset, parts[comm.inter_rank])


def scatter_index(
    n_total: int, comm, root: int = 0, force_equal_length: bool = True
):
    """Scatter only the index range [0, n_total) — rank's (start, stop) pairs
    without touching data (reference: ``scatter_index``)."""
    n_total = comm.bcast_obj(n_total, root=root)
    parts = _partition(n_total, comm.inter_size, False, None,
                       force_equal_length)
    return parts[comm.inter_rank]


class EmptyDataset:
    """Length-preserving empty stubs (reference: ``create_empty_dataset``) —
    for model-parallel processes that must iterate in lockstep but consume
    no data."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [()] * len(range(*i.indices(self._n)))
        if not -self._n <= i < self._n:
            raise IndexError(i)
        return ()


def create_empty_dataset(dataset) -> EmptyDataset:
    return EmptyDataset(len(dataset))


def shuffle_data_blocks(comm, local_block: Sequence, seed: int = 0):
    """Globally shuffle examples already distributed as per-process
    blocks (reference: ``chainermn/datasets/shuffle_datablock.py``,
    ``shuffle_data_blocks``; unverified — mount empty, see SURVEY.md).

    For datasets too large to load on one process (where
    :func:`scatter_dataset` would need everything on the root): each
    process reads its own block, then this exchanges examples so every
    process ends with a near-equal-size, *globally* shuffled subset —
    e.g. blocks read from sorted/per-class files become IID shards.

    The exchange rides ``comm.alltoall_obj`` (control-plane transport):
    a shared ``seed`` gives every process the same global permutation;
    each example's permuted position picks its destination from a
    balanced contiguous split, and receivers re-order by position so
    the result is exactly the permuted concatenation of all blocks.

    Returns this process's shuffled block (a list).
    """
    # row order of allgather_obj defines the member order; carry each
    # process's (order-defining) rank so sizes line up with it
    rows = comm.allgather_obj((comm.inter_rank, len(local_block)))
    sizes = [n for _, n in rows]
    me = [r for r, _ in rows].index(comm.inter_rank)
    total = sum(sizes)
    n_members = len(rows)

    rng = np.random.RandomState(seed)        # identical on all processes
    inv = np.empty(total, np.int64)
    inv[rng.permutation(total)] = np.arange(total)
    bounds = [total * j // n_members for j in range(n_members + 1)]

    offset = sum(sizes[:me])
    my_pos = inv[offset : offset + len(local_block)]
    dests = np.searchsorted(bounds, my_pos, side="right") - 1
    send = [[] for _ in range(n_members)]
    for i, example in enumerate(local_block):
        send[int(dests[i])].append((int(my_pos[i]), example))

    received = comm.alltoall_obj(send)
    merged = sorted(
        (item for row in received for item in row), key=lambda t: t[0])
    return [example for _, example in merged]
