"""Pure-Python byte-level BPE tokenizer — the framework's subword path.

Reference parity: the upstream seq2seq example consumed pre-tokenized
WMT text with externally-built vocabularies (reference:
``examples/seq2seq`` data pipeline; unverified — mount empty, see
SURVEY.md).  Here the tokenizer lives in the framework so the LM
example's real-text path can train an honest subword vocabulary with
zero external dependencies or network access.

Design — byte-level BPE (the GPT-2 family's scheme, minus the
regex-table complexity):

- ids ``0..255`` are the raw bytes, so ANY input round-trips exactly
  (no unknown-token case, no normalisation step to get wrong);
- merge ``i`` creates id ``256 + i`` whose byte expansion is the
  concatenation of its parts — ``decode`` is a table lookup + join;
- merges never cross a whitespace-chunk boundary (``\\s*\\S+`` or a
  whitespace run), the standard trick that keeps the pair statistics
  linguistic rather than spanning ``word1 word2`` junctions, and makes
  encoding cacheable per chunk.

Everything here is host-side data plumbing (like the rest of
``datasets/``) — tokenisation feeds the device pipeline, it never runs
under jit.
"""

from __future__ import annotations

import json
import re
from collections import Counter, defaultdict

__all__ = ["BPETokenizer", "train_bpe"]

_CHUNK = re.compile(rb"\s*\S+|\s+")


def _merge_pair(seq, pair, new_id):
    """Replace every left-to-right occurrence of adjacent ``pair`` in
    ``seq`` with ``new_id`` — the one replacement rule both encoding
    and training must share exactly (a divergence would make encoding
    disagree with the statistics training computed)."""
    out, j = [], 0
    while j < len(seq):
        if j < len(seq) - 1 and (seq[j], seq[j + 1]) == pair:
            out.append(new_id)
            j += 2
        else:
            out.append(seq[j])
            j += 1
    return tuple(out)


class BPETokenizer:
    """Byte-level BPE encoder/decoder defined entirely by its merge
    list (rank = creation order, the standard BPE contract)."""

    def __init__(self, merges):
        self.merges = [tuple(m) for m in merges]
        self.ranks = {p: i for i, p in enumerate(self.merges)}
        self._expand = {i: bytes([i]) for i in range(256)}
        for i, (a, b) in enumerate(self.merges):
            if a not in self._expand or b not in self._expand:
                raise ValueError(
                    f"merge {i} = ({a}, {b}) references an id not yet "
                    "defined — merges must be in creation order")
            self._expand[256 + i] = self._expand[a] + self._expand[b]
        self._cache: dict[bytes, tuple[int, ...]] = {}

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    # natural-language chunks (pre-tokenizer word pieces) are short and
    # highly repetitive, so the memo stays tiny; the cap only matters
    # for adversarial input (e.g. a stream of unique long chunks, where
    # the O(len^2) merge scan below would otherwise also pin unbounded
    # memory behind it).  At the cap the OLDEST entry is evicted (dict
    # preserves insertion order) instead of freezing insertion forever:
    # after an adversarial flood of unique chunks passes, steady-state
    # hot chunks re-enter the cache rather than paying the merge scan
    # on every encode for the rest of the process's life.
    _CACHE_CAP = 1 << 16

    def _encode_chunk(self, chunk: bytes) -> tuple[int, ...]:
        got = self._cache.get(chunk)
        if got is not None:
            return got
        word = tuple(chunk)
        while len(word) > 1:
            best_rank, best_pair = None, None
            for p in zip(word, word[1:]):
                r = self.ranks.get(p)
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_pair = r, p
            if best_pair is None:
                break
            word = _merge_pair(word, best_pair, 256 + best_rank)
        if len(self._cache) >= self._CACHE_CAP:
            self._cache.pop(next(iter(self._cache)))
        self._cache[chunk] = word
        return word

    def encode(self, text) -> list[int]:
        """``str`` (UTF-8-encoded first) or ``bytes`` -> token ids."""
        if isinstance(text, str):
            text = text.encode("utf-8")
        ids: list[int] = []
        for chunk in _CHUNK.findall(text):
            ids.extend(self._encode_chunk(chunk))
        return ids

    def decode(self, ids) -> bytes:
        """Token ids -> bytes.  Ids beyond the vocab (a model whose
        head is padded wider than the tokenizer can emit them early in
        training) decode to the empty string rather than raising —
        generation output should always be printable."""
        return b"".join(self._expand.get(int(i), b"") for i in ids)

    def decode_text(self, ids, errors: str = "replace") -> str:
        return self.decode(ids).decode("utf-8", errors=errors)

    def n_bytes(self, ids) -> int:
        """Byte length of the decoded ids — the denominator for
        bits-per-byte / byte-perplexity reporting, which is how a
        subword model's held-out number stays comparable to a
        byte-level baseline's."""
        return sum(len(self._expand.get(int(i), b"")) for i in ids)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "chainermn_tpu-bpe-v1",
                       "vocab_size": self.vocab_size,
                       "merges": [list(p) for p in self.merges]}, f)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            obj = json.load(f)
        tok = cls(obj["merges"])
        if obj.get("vocab_size") not in (None, tok.vocab_size):
            raise ValueError(
                f"{path}: recorded vocab_size {obj['vocab_size']} != "
                f"256 + {len(tok.merges)} merges")
        return tok


def train_bpe(data: bytes, vocab_size: int,
              min_frequency: int = 2) -> BPETokenizer:
    """Learn up to ``vocab_size - 256`` merges from ``data``.

    Classic corpus-level BPE on unique whitespace chunks weighted by
    frequency (the Sennrich formulation): pair counts live in a
    Counter, and each adopted merge re-counts only the chunks that
    contain it — O(unique chunks touched), not O(corpus), per merge.
    Stops early when no pair reaches ``min_frequency`` (merging
    singletons would just memorise the tail of the corpus).  Ties
    break deterministically (count, then pair ids) so identical input
    always yields identical merges — checkpoints depend on that.
    """
    if vocab_size <= 256:
        raise ValueError(
            f"vocab_size {vocab_size} must exceed 256 (the byte ids)")
    if not data:
        return BPETokenizer([])
    words = Counter(_CHUNK.findall(data))
    seqs = {w: tuple(w) for w in words}
    pair_counts: Counter = Counter()
    occ: defaultdict = defaultdict(set)
    for w, s in seqs.items():
        c = words[w]
        for p in zip(s, s[1:]):
            pair_counts[p] += c
            occ[p].add(w)

    merges: list[tuple[int, int]] = []
    while 256 + len(merges) < vocab_size and pair_counts:
        pair, n = max(pair_counts.items(), key=lambda kv: (kv[1], kv[0]))
        if n < min_frequency:
            break
        new_id = 256 + len(merges)
        merges.append(pair)
        for w in list(occ[pair]):
            s, c = seqs[w], words[w]
            for p in zip(s, s[1:]):
                pair_counts[p] -= c
                if pair_counts[p] <= 0:
                    del pair_counts[p]
                occ[p].discard(w)
            seqs[w] = s = _merge_pair(s, pair, new_id)
            for p in zip(s, s[1:]):
                pair_counts[p] += c
                occ[p].add(w)
    return BPETokenizer(merges)
