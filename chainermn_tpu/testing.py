"""Testing helpers — the distributed-test tooling the reference made its
users assemble by hand (SURVEY §4: ChainerMN tests ran under a real
``mpiexec -n 2`` and simply skipped when the world was too small; there
was no fake cluster).  JAX can fake both halves, and this module
packages the two tricks this repo's own suite runs on:

- :func:`ensure_virtual_pod` — an N-device virtual CPU "pod" in ONE
  process (every collective/sharding/pipeline schedule runs for real);
- :func:`run_multiprocess` — real multi-process JAX clusters on
  localhost, the TPU-native ``mpiexec -n N`` for the code paths that
  only exist across processes (object transport, checkpoint agreement,
  preemption flag reduce);
- :class:`FaultPlan` / :class:`FaultInjector` / :func:`corrupt_file` —
  the deterministic fault-injection harness: every recovery path the
  resilience layer promises (kill→resume, corrupted-latest fallback,
  watchdog stall detection, NaN abort) is exercised under an INJECTED
  fault scripted by iteration number, not by luck (docs/RESILIENCE.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal as _signal
import socket
import subprocess
import sys
import time
from typing import Optional, Sequence

__all__ = ["FaultInjector", "FaultPlan", "corrupt_file",
           "ensure_virtual_pod", "free_port", "requires_vma",
           "run_multiprocess"]


def ensure_virtual_pod(n_devices: int = 8) -> None:
    """Pin this process's JAX to an ``n_devices`` virtual CPU pod.

    MUST run before the first backend use (the first ``jax.devices()``
    locks the platform) — call it at the top of a test conftest or
    script entry point.  Idempotent if the pod is already configured;
    raises if the backend was already initialised differently (too late
    to change) or ends up with fewer devices.

    Both layers are set because env vars alone are too late when a
    sitecustomize imports jax at interpreter start (the trap this
    repo's round-1 driver gates fell into): ``XLA_FLAGS`` is read at
    backend init, and ``jax.config`` overrides any platform plugin
    registered at import time.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.device_count() < n_devices:
        raise RuntimeError(
            f"virtual pod has {jax.device_count()} devices, wanted "
            f"{n_devices} — ensure_virtual_pod must run before the "
            "first JAX backend use (jax.devices() locks the platform "
            "and XLA_FLAGS)")


def free_port() -> int:
    """An OS-assigned free TCP port (for the cluster coordinator)."""
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_multiprocess(
    worker: str,
    args: Sequence[str] = (),
    *,
    nprocs: int = 2,
    timeout: float = 180,
    pythonpath: Optional[str] = None,
):
    """Run ``worker`` (a Python file) as an ``nprocs``-process JAX CPU
    cluster on localhost — the ``mpiexec -n N`` replacement for tests.

    Each worker process receives
    ``<worker> <coordinator_addr> <nprocs> <process_id> *args`` and
    should begin with::

        import chainermn_tpu, sys
        addr, n, i = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        chainermn_tpu.init_distributed(
            coordinator_address=addr, num_processes=n, process_id=i)
        comm = chainermn_tpu.create_communicator("tpu_xla")

    The environment is scrubbed of TPU-plugin/JAX/XLA settings and each
    worker is pinned to one CPU device through BOTH layers (env var +
    a ``jax.config`` bootstrap before the worker's code runs — env vars
    alone lose when a sitecustomize imports jax at interpreter start).
    Returns the list of captured outputs; raises ``RuntimeError`` with
    every worker's output on any non-zero exit or on timeout (the usual
    symptom of a cross-process collective deadlock).
    """
    addr = f"localhost:{free_port()}"
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    if pythonpath:
        env["PYTHONPATH"] = (
            pythonpath + os.pathsep + env.get("PYTHONPATH", ""))

    bootstrap = (
        "import sys, runpy, jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "sys.argv = sys.argv[1:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", bootstrap, worker, addr, str(nprocs),
             str(i), *map(str, args)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for i in range(nprocs)
    ]
    outputs, codes = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            codes.append(p.returncode)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for p in procs:
            out, _ = p.communicate()
            outputs.append(out)
        raise RuntimeError(
            f"multiprocess worker timed out after {timeout}s (likely a "
            "cross-process collective deadlock)\n"
            + "\n---\n".join(outputs)) from None
    if any(codes):
        raise RuntimeError(
            "multiprocess workers failed:\n" + "\n".join(
                f"--- worker {i} rc={codes[i]} ---\n{outputs[i]}"
                for i in range(nprocs)))
    return outputs


def corrupt_file(path: str, n_bytes: int = 8, offset: Optional[int] = None,
                 seed: int = 0) -> list:
    """Deterministically flip ``n_bytes`` bytes of ``path`` in place.

    The corrupt-shard fault: XORs each chosen byte with a non-zero mask
    drawn from ``random.Random(seed)``, so the damage is reproducible
    and guaranteed to change the bytes (an XOR with 0 would be a no-op
    "corruption" that CRCs rightly ignore).  With ``offset=None`` the
    positions land in the middle half of the file — inside payload data
    for an uncompressed npz, past the zip local headers — which is
    exactly the damage ``verify_state`` must catch.  Returns the list of
    flipped offsets (for assertions/logging).
    """
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty — nothing to corrupt")
    rng = random.Random(seed)
    if offset is not None:
        positions = [min(offset + i, size - 1) for i in range(n_bytes)]
    else:
        lo, hi = size // 4, max(size // 4 + 1, 3 * size // 4)
        positions = sorted(rng.randrange(lo, hi) for _ in range(n_bytes))
    with open(path, "r+b") as f:
        for pos in positions:
            f.seek(pos)
            old = f.read(1)
            f.seek(pos)
            f.write(bytes([old[0] ^ rng.randrange(1, 256)]))
    return positions


@dataclasses.dataclass
class FaultPlan:
    """A deterministic fault script, keyed by iteration number.

    Every field is a plain scalar so a plan serialises through
    :meth:`to_json` / :meth:`from_json` and can be handed to a child
    process on its command line — the kill→resume drills run the faulty
    phase in a real subprocess and compare its resumed continuation
    against an uninterrupted run bitwise.

    Faults (all optional; fire at the step boundary AFTER the named
    iteration completes, where train state is consistent):

    - ``kill_at_iteration`` — ``SIGKILL`` self: the hard crash (spot
      reclamation without notice, OOM killer).  Nothing flushes.
    - ``sigterm_at_iteration`` — ``SIGTERM`` self: the preemption
      notice; with an async checkpointer on the same tick the signal
      lands MID-write, exercising the join-on-crash path.
      ``sigterm_rank`` (default ``None`` = every rank) restricts the
      signal to ONE rank — the real preemption shape, where a single
      host gets the notice and the rest learn of it through
      ``PreemptionCheckpointer``'s collective flag OR-reduce.
    - ``corrupt_at_iteration`` + ``corrupt_path`` — flip
      ``corrupt_n_bytes`` bytes of that file (:func:`corrupt_file`).
    - ``delay_at_iteration`` + ``delay_rank`` + ``delay_seconds`` —
      stall ONE rank past a watchdog threshold.
    - ``nan_at_iteration`` — poison the updater's params with NaN so
      the NEXT step's loss is non-finite (drives ``FailOnNonNumber``).
    - ``resize_at_iteration`` + ``resize_to`` — the shrink/grow drill:
      checkpoint through the injector's ``checkpointer`` (topology
      stamped) and stop the trainer cleanly, recording that the relaunch
      should run at world size ``resize_to``.  The driving test then
      rebuilds the job on the new topology and resumes through the
      checkpointer's elastic re-layout path (docs/RESILIENCE.md
      "Elastic resume").
    - ``resize_live_at_iteration`` + ``resize_live_to`` — the LIVE
      resize drill: arm the injector's ``resize_controller``
      (``training/elastic.ResizeController``) at that iteration's step
      boundary.  The controller runs at the very end of the same tick
      (priority 0 < the injector's 1), so the world changes at exactly
      the boundary a save/restart would have used — and training
      continues in the same process.
    - ``save_stall_after_files`` + ``save_stall_seconds`` — slow the
      checkpointer's per-file write hook: after the Nth file of a set
      lands, each further file waits ``save_stall_seconds`` first.
      Composed with ``kill_at_iteration`` on an async shard-only save,
      the SIGKILL deterministically lands MID-stream, leaving a partial
      covering set — the crash-during-shard-only-save drill
      (docs/RESILIENCE.md).

    Serving faults (applied by :meth:`FaultInjector.attach_engine` to a
    ``ServingEngine``, keyed by DECODE-ROUND / staging-call count
    instead of trainer iteration; each fires once):

    - ``serve_delay_at_round`` + ``serve_delay_seconds`` — stall the
      named decode round (a slow device / preempted host): deadlines
      keep being enforced, so the drill shows timeouts and shedding,
      not a hang.
    - ``serve_raise_at_round`` — the round dispatch raises (adapter
      step failure): the engine must quarantine the newest-admitted
      row and keep the remaining slots serving.
    - ``serve_exhaust_pool_at_admit`` — before the Nth staging call,
      hoard EVERY free pool block (fragmentation / leak shape);
      admission backpressures while active slots keep decoding.  The
      hoard is released after ``serve_exhaust_pool_rounds`` further
      decode rounds (recovery half of the drill).

    Fleet faults (applied by :meth:`FaultInjector.attach_fleet` to a
    ``serving.fleet.FleetRouter``, keyed by FLEET STEP count; replicas
    are named by index):

    - ``fleet_kill_at_step`` + ``fleet_kill_replica`` — that replica's
      next heartbeat at/after the step raises (host crash): the router
      must fail over — migrate its queue, re-dispatch its active rows
      from their committed prefixes — and the drill's requests must
      all still complete exactly once, token-identical to the oracle.
    - ``fleet_slow_at_step`` + ``fleet_slow_replica`` +
      ``fleet_slow_seconds`` + ``fleet_slow_steps`` — stall that
      replica's heartbeat for N consecutive steps (a degraded host):
      drives the suspect path and, with hedging enabled, the
      hedge-wins path.
    - ``fleet_flap_at_step`` + ``fleet_flap_replica`` +
      ``fleet_flap_count`` — kill/revive the replica
      ``fleet_flap_count`` times (crash-looping host): each rejoin's
      hold must grow under the router's flap damping until the
      replica is effectively out of rotation.
    """

    kill_at_iteration: Optional[int] = None
    sigterm_at_iteration: Optional[int] = None
    sigterm_rank: Optional[int] = None
    corrupt_at_iteration: Optional[int] = None
    corrupt_path: Optional[str] = None
    corrupt_n_bytes: int = 8
    delay_at_iteration: Optional[int] = None
    delay_rank: int = 0
    delay_seconds: float = 0.0
    nan_at_iteration: Optional[int] = None
    resize_at_iteration: Optional[int] = None
    resize_to: int = 0
    resize_live_at_iteration: Optional[int] = None
    resize_live_to: int = 0
    save_stall_after_files: Optional[int] = None
    save_stall_seconds: float = 0.0
    serve_delay_at_round: Optional[int] = None
    serve_delay_seconds: float = 0.0
    serve_raise_at_round: Optional[int] = None
    serve_exhaust_pool_at_admit: Optional[int] = None
    serve_exhaust_pool_rounds: int = 4
    fleet_kill_at_step: Optional[int] = None
    fleet_kill_replica: int = 0
    fleet_slow_at_step: Optional[int] = None
    fleet_slow_replica: int = 0
    fleet_slow_seconds: float = 0.0
    fleet_slow_steps: int = 1
    fleet_flap_at_step: Optional[int] = None
    fleet_flap_replica: int = 0
    fleet_flap_count: int = 2
    seed: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, payload: str) -> "FaultPlan":
        return cls(**json.loads(payload))


class FaultInjector:
    """Trainer extension applying a :class:`FaultPlan`.

    LOWEST priority (runs last on its tick, after log writers and the
    checkpointer): a kill fires only once everything that tick promised
    to persist has at least STARTED persisting — which for an async
    checkpoint write means the signal really lands mid-write.
    """

    trigger = (1, "iteration")
    priority = 1

    def __init__(self, plan: FaultPlan, comm=None, checkpointer=None,
                 resize_controller=None):
        self.plan = plan
        self.comm = comm
        # the resize action saves through a real checkpointer so the
        # stopped state is topology-stamped for the elastic relaunch
        self.checkpointer = checkpointer
        # the LIVE resize action arms this controller instead of
        # stopping the trainer (training/elastic.ResizeController)
        self.resize_controller = resize_controller
        self.fired: list = []
        if checkpointer is not None \
                and plan.save_stall_after_files is not None:
            self._attach_save_stall(checkpointer)

    def _attach_save_stall(self, checkpointer) -> None:
        """Wrap the checkpointer's per-file write hook so every file
        after the plan's Nth sleeps first — pins a concurrent SIGKILL
        mid-stream (deterministic partial covering set)."""
        plan = self.plan
        real = checkpointer._write_part
        state = {"files": 0}

        def stalled(path, tree, topology, shard_part):
            if state["files"] >= plan.save_stall_after_files:
                self.fired.append(("save_stall", state["files"]))
                time.sleep(plan.save_stall_seconds)
            real(path, tree, topology, shard_part)
            state["files"] += 1

        checkpointer._write_part = stalled

    def _rank(self) -> int:
        return getattr(self.comm, "inter_rank", 0) if self.comm else 0

    def __call__(self, trainer) -> None:
        plan = self.plan
        it = trainer.updater.iteration
        if plan.nan_at_iteration == it:
            import jax
            import jax.numpy as jnp

            trainer.updater.params = jax.tree.map(
                lambda a: a * jnp.nan, trainer.updater.params)
            self.fired.append(("nan", it))
        if (plan.delay_at_iteration == it
                and self._rank() == plan.delay_rank):
            self.fired.append(("delay", it))
            time.sleep(plan.delay_seconds)
        if plan.corrupt_at_iteration == it and plan.corrupt_path:
            corrupt_file(plan.corrupt_path, plan.corrupt_n_bytes,
                         seed=plan.seed)
            self.fired.append(("corrupt", it))
        if plan.resize_live_at_iteration == it:
            if self.resize_controller is None:
                raise RuntimeError(
                    "FaultPlan.resize_live_at_iteration needs "
                    "FaultInjector(resize_controller=...) — the live "
                    "resize is performed by a ResizeController "
                    "extension on the same tick")
            self.resize_controller.request(plan.resize_live_to)
            self.fired.append(("resize_live", it, plan.resize_live_to))
        if plan.resize_at_iteration == it:
            if self.checkpointer is None:
                raise RuntimeError(
                    "FaultPlan.resize_at_iteration needs "
                    "FaultInjector(checkpointer=...) — the resize drill "
                    "must save a topology-stamped snapshot to resume "
                    "from")
            self.checkpointer.save(trainer.updater, trainer)
            self.fired.append(("resize", it, plan.resize_to))
            trainer.stop(
                f"elastic resize drill: snapshot saved at iteration "
                f"{it}; relaunch at world={plan.resize_to}")
        if plan.sigterm_at_iteration == it and (
                plan.sigterm_rank is None
                or self._rank() == plan.sigterm_rank):
            self.fired.append(("sigterm", it))
            os.kill(os.getpid(), _signal.SIGTERM)
        if plan.kill_at_iteration == it:
            # flush stdio so the phase's progress log survives the kill
            sys.stdout.flush()
            sys.stderr.flush()
            os.kill(os.getpid(), _signal.SIGKILL)

    _FAULT_HOARD = "__fault_pool_hoard__"

    def attach_engine(self, engine):
        """Apply the plan's SERVING faults to a ``ServingEngine`` by
        wrapping its decode-round dispatch and staging path (host-side
        wrappers — no recompile, no engine code knows it is under
        test).  Round-keyed faults count ROUND DISPATCHES (including
        failed ones), pool exhaustion counts STAGING calls.  Each
        fault fires once; firings append to :attr:`fired` as
        ``("serve_<kind>", count)``.  Returns the engine."""
        plan = self.plan
        # "ticks" = round dispatches + staging attempts: the release
        # countdown must advance even when the pool hoard has idled
        # every slot (no live rows -> no rounds, but each blocked
        # admit attempt still stages)
        state = {"rounds": 0, "stages": 0, "ticks": 0,
                 "hoard_until": None}
        real_round = engine._round_fn
        real_stage = engine._stage

        def maybe_release():
            if (state["hoard_until"] is not None
                    and state["ticks"] >= state["hoard_until"]):
                engine._alloc.free_row(self._FAULT_HOARD)
                state["hoard_until"] = None
                self.fired.append(("serve_pool_release", state["ticks"]))

        def round_wrapper(*args, **kwargs):
            r = state["rounds"]
            state["rounds"] += 1
            state["ticks"] += 1
            if plan.serve_delay_at_round == r:
                self.fired.append(("serve_delay", r))
                time.sleep(plan.serve_delay_seconds)
            if plan.serve_raise_at_round == r:
                self.fired.append(("serve_raise", r))
                raise RuntimeError(
                    "injected decode-round failure "
                    "(FaultPlan.serve_raise_at_round)")
            out = real_round(*args, **kwargs)
            maybe_release()
            return out

        def stage_wrapper(req, rec, steal, idle=True):
            n = state["stages"]
            state["stages"] += 1
            state["ticks"] += 1
            if (plan.serve_exhaust_pool_at_admit == n
                    and self._FAULT_HOARD not in engine._alloc.rows()):
                # cache-only prefix blocks are reclaimable on demand,
                # so a faithful exhaustion drill must hoard them too
                reclaim = getattr(engine._alloc, "reclaim", None)
                if reclaim is not None:
                    reclaim(engine._alloc.n_blocks)
                engine._alloc.alloc(self._FAULT_HOARD,
                                    engine._alloc.n_free)
                state["hoard_until"] = (
                    state["ticks"] + plan.serve_exhaust_pool_rounds)
                self.fired.append(("serve_pool_exhaust", n))
            out = real_stage(req, rec, steal, idle=idle)
            maybe_release()
            return out

        engine._round_fn = round_wrapper
        engine._stage = stage_wrapper
        return engine

    def attach_fleet(self, router):
        """Apply the plan's FLEET faults to a
        ``serving.fleet.FleetRouter`` by wrapping its per-replica
        heartbeat (``_step_replica``) and its ``step`` (host-side
        wrappers, same discipline as :meth:`attach_engine` — the
        router never knows it is under test).  Faults key on the
        router's OWN step counter, replicas on their index.  Firings
        append to :attr:`fired` as ``("fleet_<kind>", step)``.
        Returns the router."""
        plan = self.plan
        names = [h.name for h in router.replicas]

        def target(idx):
            return names[idx] if 0 <= idx < len(names) else None

        kill_name = target(plan.fleet_kill_replica)
        slow_name = target(plan.fleet_slow_replica)
        flap_name = target(plan.fleet_flap_replica)
        state = {"killed": False, "slowed": 0,
                 "flap_kills": 0, "flap_revives": 0}
        real_step_replica = router._step_replica
        real_step = router.step

        def step_replica_wrapper(h):
            step = router.step_count
            if (plan.fleet_kill_at_step is not None
                    and h.name == kill_name and not state["killed"]
                    and step >= plan.fleet_kill_at_step):
                state["killed"] = True
                self.fired.append(("fleet_kill", step))
                raise RuntimeError(
                    "injected replica crash "
                    "(FaultPlan.fleet_kill_at_step)")
            if (plan.fleet_flap_at_step is not None
                    and h.name == flap_name
                    and state["flap_kills"] < plan.fleet_flap_count
                    and step >= plan.fleet_flap_at_step):
                state["flap_kills"] += 1
                self.fired.append(("fleet_flap_kill", step))
                raise RuntimeError(
                    "injected replica flap "
                    "(FaultPlan.fleet_flap_at_step)")
            if (plan.fleet_slow_at_step is not None
                    and h.name == slow_name
                    and step >= plan.fleet_slow_at_step
                    and state["slowed"] < plan.fleet_slow_steps):
                state["slowed"] += 1
                self.fired.append(("fleet_slow", step))
                time.sleep(plan.fleet_slow_seconds)
            return real_step_replica(h)

        def step_wrapper():
            out = real_step()
            # the flap's revive half: the crash-looping host comes
            # straight back, so the ROUTER's damping (not the host's
            # absence) is what must contain it
            if (plan.fleet_flap_at_step is not None
                    and flap_name is not None
                    and state["flap_revives"] < state["flap_kills"]):
                h = router._by_name[flap_name]
                if h.state == "dead":
                    router.revive(flap_name)
                    state["flap_revives"] += 1
                    self.fired.append(
                        ("fleet_flap_revive", router.step_count))
            return out

        router._step_replica = step_replica_wrapper
        router.step = step_wrapper
        return router


def requires_vma(reason: str = "requires vma-typed shard_map"):
    """``pytest.mark.skipif`` for tests whose SEMANTICS need vma-typed
    shard_map (``parallel._compat.HAS_VMA`` documents which those are:
    custom VJPs reading ``typeof(x).vma``, grads of replicated outputs,
    rep-gaining scan carries, ...).  One definition instead of a
    copy-pasted skipif block per test file; lazy pytest import so the
    package itself never depends on pytest.  Use as::

        pytestmark = cmn.testing.requires_vma()
    """
    import pytest

    from chainermn_tpu.parallel._compat import HAS_VMA

    return pytest.mark.skipif(not HAS_VMA, reason=reason)
