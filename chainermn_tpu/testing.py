"""Testing helpers — the distributed-test tooling the reference made its
users assemble by hand (SURVEY §4: ChainerMN tests ran under a real
``mpiexec -n 2`` and simply skipped when the world was too small; there
was no fake cluster).  JAX can fake both halves, and this module
packages the two tricks this repo's own suite runs on:

- :func:`ensure_virtual_pod` — an N-device virtual CPU "pod" in ONE
  process (every collective/sharding/pipeline schedule runs for real);
- :func:`run_multiprocess` — real multi-process JAX clusters on
  localhost, the TPU-native ``mpiexec -n N`` for the code paths that
  only exist across processes (object transport, checkpoint agreement,
  preemption flag reduce).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Optional, Sequence

__all__ = ["ensure_virtual_pod", "run_multiprocess", "free_port",
           "requires_vma"]


def ensure_virtual_pod(n_devices: int = 8) -> None:
    """Pin this process's JAX to an ``n_devices`` virtual CPU pod.

    MUST run before the first backend use (the first ``jax.devices()``
    locks the platform) — call it at the top of a test conftest or
    script entry point.  Idempotent if the pod is already configured;
    raises if the backend was already initialised differently (too late
    to change) or ends up with fewer devices.

    Both layers are set because env vars alone are too late when a
    sitecustomize imports jax at interpreter start (the trap this
    repo's round-1 driver gates fell into): ``XLA_FLAGS`` is read at
    backend init, and ``jax.config`` overrides any platform plugin
    registered at import time.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.device_count() < n_devices:
        raise RuntimeError(
            f"virtual pod has {jax.device_count()} devices, wanted "
            f"{n_devices} — ensure_virtual_pod must run before the "
            "first JAX backend use (jax.devices() locks the platform "
            "and XLA_FLAGS)")


def free_port() -> int:
    """An OS-assigned free TCP port (for the cluster coordinator)."""
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_multiprocess(
    worker: str,
    args: Sequence[str] = (),
    *,
    nprocs: int = 2,
    timeout: float = 180,
    pythonpath: Optional[str] = None,
):
    """Run ``worker`` (a Python file) as an ``nprocs``-process JAX CPU
    cluster on localhost — the ``mpiexec -n N`` replacement for tests.

    Each worker process receives
    ``<worker> <coordinator_addr> <nprocs> <process_id> *args`` and
    should begin with::

        import chainermn_tpu, sys
        addr, n, i = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        chainermn_tpu.init_distributed(
            coordinator_address=addr, num_processes=n, process_id=i)
        comm = chainermn_tpu.create_communicator("tpu_xla")

    The environment is scrubbed of TPU-plugin/JAX/XLA settings and each
    worker is pinned to one CPU device through BOTH layers (env var +
    a ``jax.config`` bootstrap before the worker's code runs — env vars
    alone lose when a sitecustomize imports jax at interpreter start).
    Returns the list of captured outputs; raises ``RuntimeError`` with
    every worker's output on any non-zero exit or on timeout (the usual
    symptom of a cross-process collective deadlock).
    """
    addr = f"localhost:{free_port()}"
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    if pythonpath:
        env["PYTHONPATH"] = (
            pythonpath + os.pathsep + env.get("PYTHONPATH", ""))

    bootstrap = (
        "import sys, runpy, jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "sys.argv = sys.argv[1:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", bootstrap, worker, addr, str(nprocs),
             str(i), *map(str, args)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for i in range(nprocs)
    ]
    outputs, codes = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            codes.append(p.returncode)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for p in procs:
            out, _ = p.communicate()
            outputs.append(out)
        raise RuntimeError(
            f"multiprocess worker timed out after {timeout}s (likely a "
            "cross-process collective deadlock)\n"
            + "\n---\n".join(outputs)) from None
    if any(codes):
        raise RuntimeError(
            "multiprocess workers failed:\n" + "\n".join(
                f"--- worker {i} rc={codes[i]} ---\n{outputs[i]}"
                for i in range(nprocs)))
    return outputs


def requires_vma(reason: str = "requires vma-typed shard_map"):
    """``pytest.mark.skipif`` for tests whose SEMANTICS need vma-typed
    shard_map (``parallel._compat.HAS_VMA`` documents which those are:
    custom VJPs reading ``typeof(x).vma``, grads of replicated outputs,
    rep-gaining scan carries, ...).  One definition instead of a
    copy-pasted skipif block per test file; lazy pytest import so the
    package itself never depends on pytest.  Use as::

        pytestmark = cmn.testing.requires_vma()
    """
    import pytest

    from chainermn_tpu.parallel._compat import HAS_VMA

    return pytest.mark.skipif(not HAS_VMA, reason=reason)
