"""Live introspection — an opt-in HTTP surface for a running job.

Every observability layer so far writes FILES (Prometheus textfiles,
JSONL series, trace shards) an operator reads after the fact.  A
production serving fleet also needs the live question answered NOW:
is this process healthy, what is its queue depth, which epoch is it
serving, which alerts are firing, and show me the trace of that slow
request.  This module is that surface — a stdlib-only
(:mod:`http.server`) daemon thread serving five endpoints:

- ``/healthz`` — liveness + registered health checks; HTTP 200 while
  every check passes, 503 otherwise (the load-balancer probe).
- ``/metricsz`` — the metrics registry as Prometheus exposition text
  (the pull-scrape twin of the ``MetricsTextfile`` push); exemplars
  ride when negotiated (openmetrics ``Accept`` or ``?exemplars=1``),
  classic 0.0.4 stays clean.
- ``/statusz`` — one JSON document of live state *sections*: engine
  queue depth / active slots / shed taxonomy / serving epoch + drain
  state (:meth:`attach_engine`), live-resize epochs
  (``ResizeController.status``), updater progress
  (``StandardUpdater.status``), burn-rate alert state, and a compact
  counter/gauge digest (plan-cache hits, goodput) — plus any section
  a caller registers.
- ``/tracez`` — the retained request traces
  (:class:`~chainermn_tpu.utils.telemetry.RequestTraceStore`):
  newest-first summaries, ``?trace_id=`` resolves one full timeline
  (the last hop of the exemplar link), ``?chrome=1`` renders the
  Perfetto document.
- ``/programz`` — the compile-and-memory plane
  (:mod:`chainermn_tpu.utils.programs`): the XLA program ledger
  newest-first (each compile with its signature diff — the "why did
  this retrace" attribution), per-label compile/call stats, and the
  memory accountant's per-subsystem byte table with high-watermarks
  (``?n=`` bounds the entry list, ``?scope=serve/`` restricts to one
  subsystem's labels).

Discipline matches the rest of the stack: OFF by default, explicitly
constructed (or env-gated — ``CHAINERMN_TPU_STATUSZ=1`` serves on an
ephemeral port, ``=<port>`` on a fixed one, via
:func:`start_from_env`), binds loopback unless told otherwise (this
is an introspection port, not a public API), and no handler exception
can ever propagate into the serving/training loop — a broken section
renders as its error string.  Pure stdlib; importable without jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["StatuszServer", "start_from_env"]


def _json_safe(obj):
    """Best-effort JSON coercion for section payloads (numpy scalars,
    tuples, stray objects) — an introspection page must render what it
    can, not 500 on one exotic value."""
    return json.loads(json.dumps(obj, default=str))


class _Handler(BaseHTTPRequestHandler):
    server_version = "chainermn-tpu-statusz/1"

    def log_message(self, format, *args):   # noqa: A002 — stdlib name
        pass        # no stderr spam from scrapers

    # -- plumbing ------------------------------------------------------ #

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload, indent=1, default=str),
                   "application/json")

    # -- routes -------------------------------------------------------- #

    def do_GET(self) -> None:           # noqa: N802 — stdlib protocol
        try:
            owner: "StatuszServer" = self.server.statusz
            path, _, query = self.path.partition("?")
            params = urllib.parse.parse_qs(query)
            if path == "/healthz":
                self._healthz(owner)
            elif path in ("/metricsz", "/metrics"):
                self._metricsz(owner, params)
            elif path == "/statusz":
                self._send_json(200, owner.statusz())
            elif path == "/tracez":
                self._tracez(owner, params)
            elif path == "/programz":
                self._programz(owner, params)
            else:
                self._send_json(404, {
                    "error": f"no route {path!r}",
                    "routes": ["/healthz", "/metricsz", "/programz",
                               "/statusz", "/tracez"]})
        except Exception as err:        # noqa: BLE001 — introspection
            try:                        # must never kill the server
                self._send_json(500, {"error": f"{type(err).__name__}: "
                                               f"{err}"})
            except Exception:
                pass

    def _healthz(self, owner: "StatuszServer") -> None:
        checks, healthy = owner.health()
        self._send_json(200 if healthy else 503, {
            "status": "ok" if healthy else "unhealthy",
            "uptime_s": round(time.monotonic() - owner._t_start, 3),
            "checks": checks,
        })

    def _metricsz(self, owner: "StatuszServer", params) -> None:
        from chainermn_tpu.utils.metrics import to_prometheus

        reg = owner._registry()
        # exemplar suffixes are OPENMETRICS grammar — classic 0.0.4
        # parsers reject the row — so they ride only a negotiated
        # openmetrics exposition (Accept header, the scrape protocol)
        # or an explicit ?exemplars=1 (the human/debug opt-in)
        want_om = ("openmetrics"
                   in (self.headers.get("Accept") or "")) \
            or (params.get("exemplars") or ["0"])[0] not in ("", "0")
        text = to_prometheus(reg, labels=owner.labels,
                             openmetrics=want_om)
        if want_om:
            self._send(200, text,
                       "application/openmetrics-text; version=1.0.0; "
                       "charset=utf-8")
        else:
            self._send(200, text, "text/plain; version=0.0.4")

    def _tracez(self, owner: "StatuszServer", params) -> None:
        trace_id = (params.get("trace_id") or [None])[0]
        chrome = (params.get("chrome") or ["0"])[0] not in ("", "0")
        if trace_id is not None:
            for store in owner.trace_stores:
                tr = store.get(trace_id)
                if tr is not None:
                    if chrome:
                        self._send_json(200, store.to_chrome(trace_id))
                    else:
                        self._send_json(200, {"trace": _json_safe(tr)})
                    return
            self._send_json(404, {"error": f"trace {trace_id!r} not "
                                           "retained"})
            return
        if chrome and owner.trace_stores:
            # every registered store rides one document; lanes are
            # (pid, tid) so later stores' request tids are offset past
            # the earlier ones to keep them distinct under a shared pid
            doc = owner.trace_stores[0].to_chrome()
            for store in owner.trace_stores[1:]:
                offset = 1 + max(
                    (ev.get("tid", 0) for ev in doc["traceEvents"]),
                    default=0)
                extra = store.to_chrome()
                for ev in extra["traceEvents"]:
                    ev["tid"] = ev.get("tid", 0) + offset
                doc["traceEvents"].extend(extra["traceEvents"])
            self._send_json(200, doc)
            return
        try:
            n = int((params.get("n") or ["64"])[0])
        except ValueError:
            n = 64          # typo'd knob degrades, never a 500
        if n < 0:
            n = 64
        stores = []
        traces = []
        for store in owner.trace_stores:
            stores.append(store.snapshot())
            # store.traces(n) is the newest n in oldest-first order;
            # the page serves newest FIRST (the incident-reading order
            # the module docstring promises)
            for tr in reversed(store.traces(n)):
                traces.append({
                    "trace_id": tr.get("trace_id"),
                    "rid": tr.get("rid"),
                    "status": tr.get("status"),
                    "reason": tr.get("reason"),
                    "slo_violated": tr.get("slo_violated"),
                    "e2e": tr.get("e2e"),
                    "ttft": tr.get("ttft"),
                    "spans": len(tr.get("spans", ())),
                })
        self._send_json(200, {"stores": stores,
                              "traces": _json_safe(traces)})

    def _programz(self, owner: "StatuszServer", params) -> None:
        try:
            n = int((params.get("n") or ["64"])[0])
        except ValueError:
            n = 64          # typo'd knob degrades, never a 500
        scope = (params.get("scope") or [None])[0]
        self._send_json(200, owner.programz(n=n, scope=scope))


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class StatuszServer:
    """The ops-plane HTTP thread (see module docstring).

    Args:
      port: TCP port; 0 (the default) binds an ephemeral one —
        :meth:`start` returns the real port.
      host: bind address; loopback by default.
      registry: metrics registry ``/metricsz`` renders (default: the
        process-global one, resolved per request so ``set_registry``
        swaps are honored).
      alerts: an :class:`~chainermn_tpu.utils.alerts.AlertManager`
        whose state becomes the ``alerts`` statusz section (default:
        whatever :func:`~chainermn_tpu.utils.alerts.get_installed`
        finds at request time).
      labels: extra Prometheus labels on every ``/metricsz`` sample
        (e.g. ``{"rank": "0"}``).

    Sections are ``name -> zero-arg callable`` returning a JSON-safe
    dict; register with :meth:`add_section` (or :meth:`attach_engine`
    / any object exposing ``.status()`` — ``ResizeController`` and
    ``StandardUpdater`` do).  A section that raises renders as its
    error string: one broken producer must not blank the page.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 registry=None, alerts=None, ledger=None,
                 accountant=None,
                 labels: Optional[Dict[str, str]] = None):
        self.requested_port = int(port)
        self.host = host
        self.registry = registry
        self.alerts = alerts
        self.ledger = ledger
        self.accountant = accountant
        self.labels = labels
        self._sections: Dict[str, Callable[[], dict]] = {}
        self._health: Dict[str, Callable[[], bool]] = {}
        self._trace_sources: list = []
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.monotonic()

    # -- wiring -------------------------------------------------------- #

    def add_section(self, name: str, source) -> "StatuszServer":
        """Register a ``/statusz`` section: an object exposing
        ``.status()`` (preferred — trainer extensions are themselves
        callable, with the wrong signature), or a zero-arg callable."""
        fn = getattr(source, "status", None)
        if not callable(fn):
            fn = source if callable(source) else None
        if fn is None:
            raise TypeError(
                f"section {name!r}: need a callable or an object with "
                f".status(), got {type(source).__name__}")
        self._sections[str(name)] = fn
        return self

    def add_health(self, name: str,
                   check: Callable[[], bool]) -> "StatuszServer":
        """Register a ``/healthz`` check (truthy = healthy; raising =
        unhealthy with the exception as detail)."""
        self._health[str(name)] = check
        return self

    def add_traces(self, store) -> "StatuszServer":
        """Serve retained request traces on ``/tracez``.  ``store`` is
        a :class:`~chainermn_tpu.utils.telemetry.RequestTraceStore` or
        a zero-arg callable resolved PER REQUEST (how
        :meth:`attach_engine` binds — tracing enabled mid-incident is
        picked up by the very next scrape)."""
        if store is not None and store not in self._trace_sources:
            self._trace_sources.append(store)
        return self

    @property
    def trace_stores(self) -> list:
        """The live trace stores, resolved at request time (callable
        sources re-read, ``None`` results dropped, duplicates folded)."""
        stores = []
        for src in self._trace_sources:
            store = src() if callable(src) else src
            if store is not None and store not in stores:
                stores.append(store)
        return stores

    def attach_engine(self, engine,
                      name: str = "serving") -> "StatuszServer":
        """Wire a :class:`~chainermn_tpu.serving.ServingEngine`: its
        ``stats()`` (+ active slots and trace-store retention counters)
        becomes a section, its trace store feeds ``/tracez`` (resolved
        per request — a store installed on the engine AFTER attach is
        served too), and a health check asserts the engine still
        answers."""

        def section():
            st = engine.stats()
            st["active_slots"] = engine.n_active
            traces = getattr(engine, "traces", None)
            if traces is not None:
                st["traces"] = traces.snapshot()
            return st

        self.add_section(name, section)
        self.add_traces(lambda: getattr(engine, "traces", None))
        self.add_health(name, lambda: engine.stats() is not None)
        return self

    # -- request-time state -------------------------------------------- #

    def _registry(self):
        if self.registry is not None:
            return self.registry
        from chainermn_tpu.utils.metrics import get_registry

        return get_registry()

    def _alerts(self):
        if self.alerts is not None:
            return self.alerts
        from chainermn_tpu.utils.alerts import get_installed

        return get_installed()

    def _ledger(self):
        if self.ledger is not None:
            return self.ledger
        from chainermn_tpu.utils.programs import get_ledger

        return get_ledger()

    def _accountant(self):
        if self.accountant is not None:
            return self.accountant
        from chainermn_tpu.utils.programs import get_accountant

        return get_accountant()

    def programz(self, n: int = 64,
                 scope: Optional[str] = None) -> dict:
        """The ``/programz`` document: the program ledger's summary +
        newest-first compile entries (each with its signature diff —
        the "why did this retrace" read), and the memory accountant's
        per-subsystem byte table with high-watermarks.  ``scope``
        restricts the entry list to a label prefix (``?scope=serve/``
        — the incident view of one subsystem's programs)."""
        led = self._ledger()
        acc = self._accountant()
        doc = {"ts": time.time()}
        # each block renders (or errors) independently — one broken
        # producer must not blank the others (the section discipline)
        try:
            doc["ledger"] = _json_safe(led.status())
        except Exception as err:        # noqa: BLE001 — introspection
            doc["ledger"] = {"error": f"{type(err).__name__}: {err}"}
        try:
            doc["programs"] = _json_safe(led.entries(n, scope=scope))
        except Exception as err:        # noqa: BLE001
            doc["programs"] = {"error": f"{type(err).__name__}: {err}"}
        try:
            # sample() refreshes the gauges — on THIS server's
            # configured registry, the one /metricsz renders — so a
            # scrape never shows a stale (or absent) memory table;
            # with nothing registered it is an empty walk
            acc.sample(registry=self._registry())
            doc["memory"] = _json_safe(acc.table())
        except Exception as err:        # noqa: BLE001
            doc["memory"] = {"error": f"{type(err).__name__}: {err}"}
        return doc

    def health(self):
        checks = {}
        healthy = True
        for name, fn in self._health.items():
            try:
                ok = bool(fn())
            except Exception as err:    # noqa: BLE001
                ok = False
                checks[name] = f"error: {type(err).__name__}: {err}"
            else:
                checks[name] = "ok" if ok else "failing"
            healthy &= ok
        return checks, healthy

    def statusz(self) -> dict:
        reg = self._registry()
        # counters/gauges only — a full reg.snapshot() would also
        # serialize every histogram's retained samples + exemplars
        # per scrape just to be thrown away here
        fn = getattr(reg, "digest", None)
        digest = fn() if callable(fn) else {}
        doc = {
            "ts": time.time(),
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "metrics_enabled": bool(getattr(reg, "enabled", False)),
            "counters": digest,
            "sections": {},
        }
        mgr = self._alerts()
        if mgr is not None:
            try:
                doc["alerts"] = mgr.state()
            except Exception as err:    # noqa: BLE001
                doc["alerts"] = {"error": f"{type(err).__name__}: "
                                          f"{err}"}
        for name, fn in self._sections.items():
            try:
                doc["sections"][name] = _json_safe(fn())
            except Exception as err:    # noqa: BLE001
                doc["sections"][name] = {
                    "error": f"{type(err).__name__}: {err}"}
        return doc

    # -- lifecycle ----------------------------------------------------- #

    @property
    def port(self) -> Optional[int]:
        """The bound port (``None`` before :meth:`start`)."""
        return (self._server.server_address[1]
                if self._server is not None else None)

    def url(self, path: str = "/statusz") -> str:
        if self._server is None:
            raise RuntimeError("statusz server not started")
        return f"http://{self.host}:{self.port}{path}"

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port.
        Idempotent."""
        if self._server is not None:
            return self.port
        server = _Server((self.host, self.requested_port), _Handler)
        server.statusz = self
        self._server = server
        self._t_start = time.monotonic()
        self._thread = threading.Thread(
            target=server.serve_forever, name="statusz",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def start_from_env(**kwargs) -> Optional[StatuszServer]:
    """The env opt-in: ``CHAINERMN_TPU_STATUSZ`` unset/``0`` → no
    server (returns ``None``); ``1``/``auto`` → start on an ephemeral
    port; any other integer → that port.  Extra kwargs (sections,
    registry, ...) pass through to :class:`StatuszServer`."""
    raw = os.environ.get("CHAINERMN_TPU_STATUSZ", "")
    if raw in ("", "0"):
        return None
    try:
        port = 0 if raw in ("1", "auto") else int(raw)
    except ValueError:
        # the typo'd-knob-degrades discipline (engine's
        # _trace_store_from_env): the operator clearly wanted the
        # surface on — serve on an ephemeral port, never crash the job
        port = 0
    if not 0 <= port <= 65535:
        port = 0
    srv = StatuszServer(port=port, **kwargs)
    try:
        srv.start()
    except OSError:
        if port == 0:
            return None     # can't bind at all: introspection only
        srv = StatuszServer(port=0, **kwargs)   # port taken: degrade
        try:
            srv.start()
        except OSError:
            return None
    return srv
