"""Compile-and-memory plane — the XLA program ledger and the
device-memory accountant.

Every measured win in this stack rides an XLA program, and until now
the programs themselves were dark: a retrace storm (the serving
engine's per-(prefix,suffix)-split ``verify`` compiles, the epoch-tail
shapes, a post-resize recompile) or a device-memory creep (staging
pool vs params vs ZeRO shards) was only ever discovered AFTER it ate a
bench run.  GC3 (PAPERS.md) treats communication programs as
inspectable compile-time artifacts rather than opaque lowered blobs;
this module applies the same stance to every jit program the stack
builds.  Two instruments:

- :class:`ProgramLedger` — a process-global bounded ring of compile
  events.  Call sites wrap their jitted programs through
  :func:`ledger_jit` (or :func:`instrument` for an already-jitted fn);
  the wrapper computes the abstract argument signature per call (leaf
  shapes/dtypes + tree structure — exactly what decides a jit retrace)
  and, on a signature never seen for that label, times the call and
  records a ledger entry: label, signature digest, compile wall time
  (the first-call wall time — tracing + XLA compile + the first
  execution, the cost an operator actually pays), the donation map,
  and a **signature diff vs the previous entry for that label** — the
  "why did this retrace" attribution (dtype flip vs shape change vs
  sharding change vs structure change vs donation change).  Signature-identical calls pay
  one set lookup and dispatch straight through; a disabled ledger is
  one attribute read (the PR 6/9 singleton discipline — nothing is
  allocated or retained, pinned by test).

  Each compile event also fans out through the existing plane: a
  ``compile/<label>`` span in the flight recorder, a
  ``compile/seconds`` histogram observation (exemplar → the current
  request trace id when the engine staged one), ``compile/retraces``
  + per-label ``compile/retraces_<label>`` counters, and — after
  :meth:`~ProgramLedger.mark_steady` declares a label prefix
  steady-state — ``compile/steady_retraces``, the feed of the
  retrace-storm alert (:func:`retrace_storm_rule`).  Zero
  steady-state recompiles is a pinned invariant: the serving decode
  loop post-warm and the accum training loop post-step-1 each carry a
  ledger-backed test proving no compiles after warmup.

- :class:`MemoryAccountant` — per-subsystem live-buffer byte gauges.
  Subsystems register their buffer roots (``params``, optimizer
  state, the serving staging pool, prefix-cache pools, prefetch
  slots) as pytrees or zero-arg callables; :meth:`~MemoryAccountant
  .sample` walks the leaves into ``memory/<subsystem>_bytes`` gauges
  (per-addressable-shard bytes when the leaf is a sharded jax array —
  replication counts, the device question is "how much HBM is held",
  not "how large is the logical array") plus ``memory/total_bytes``.
  The gauge's max IS the high-watermark, and gauge cross-rank merge
  (max-of-max) is order-independent, so merged fleet watermarks are
  deterministic whatever order ranks fold in (pinned by test).

``/programz`` (:mod:`chainermn_tpu.utils.statusz`) serves both live:
the newest-first ledger with signature diffs and the per-subsystem
memory table.  ``GoodputReport`` reads the ledger's cumulative compile
seconds per window (``train/`` labels only) into a ``compile`` badput
category, so a post-resize recompile shows up in the goodput
decomposition instead of hiding inside "host-blocked"
(``rebind_world`` calls :meth:`ProgramLedger.forget`, so the
recompile is recorded even at a previously-seen signature).  ``bench_programs.py`` pins the
ledger+accountant overhead < 1%; ``CHAINERMN_TPU_PROGRAMS=1`` enables
the global ledger at import.

Importable without jax (only the stdlib and the equally jax-free
metrics/telemetry siblings load at import; jax resolves lazily inside
the wrappers), so the module stays usable from the iterator layer and
from tooling that never touches an accelerator.
"""

from __future__ import annotations

import collections
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.utils.metrics import get_registry
from chainermn_tpu.utils.telemetry import get_recorder

__all__ = [
    "MemoryAccountant",
    "ProgramLedger",
    "abstract_signature",
    "get_accountant",
    "get_ledger",
    "instrument",
    "ledger_jit",
    "retrace_storm_rule",
    "set_accountant",
    "set_ledger",
    "signature_diff",
    "weakref_root",
]


def _slug(name: str) -> str:
    """A label as a metric-name suffix: lowercase, ``[a-z0-9_]`` only
    (``serve/chunk_prefill`` → ``serve_chunk_prefill``) — the
    dynamic-family convention ``serve/shed_<reason>`` established."""
    return re.sub(r"[^a-z0-9_]", "_", str(name).lower())


# ---------------------------------------------------------------------- #
# abstract signatures
# ---------------------------------------------------------------------- #

def _leaf_key(x):
    """One leaf's abstract signature as a cheap hashable key —
    ``(shape, dtype, sharding)`` for anything array-like, the bare
    type for a python scalar (scalars trace by type, not value —
    value changes do not retrace).  SHARDING is part of the key
    because it is part of jit's: a feed suddenly arriving committed
    to a different layout (a stale-mesh ``device_put`` after a
    resize) recompiles every call, and a ledger blind to it would
    read that storm as healthy.  A host array (numpy) carries no
    sharding and keys as ``None`` there.  No string work on the hot
    path; :func:`format_leaf` renders the human form only when a
    compile is recorded."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return type(x)
    sharding = getattr(x, "sharding", None)
    if sharding is not None:
        try:
            hash(sharding)
        except TypeError:       # exotic array-like: drop, never crash
            sharding = None
    return (tuple(shape), dtype, sharding)


def format_leaf(key) -> str:
    """The readable form of a :func:`_leaf_key`:
    ``dtype[d0,d1,...]`` (``@sharding`` appended when the leaf
    carried one) or ``py:<type>`` — what ledger entries, diffs and
    /programz render."""
    if isinstance(key, type):
        return f"py:{key.__name__}"
    shape, dtype, sharding = key
    base = f"{dtype}[{','.join(str(int(d)) for d in shape)}]"
    return base if sharding is None else f"{base}@{sharding}"


def abstract_signature(args: tuple) -> Tuple[Any, Tuple[str, ...]]:
    """``(treedef, per-leaf signatures)`` for a call's positional
    args, human-readable form — the pair that decides whether jit
    retraces (modulo weak-type promotion, which only ever COALESCES
    signatures; a signature the ledger has seen can never recompile).
    The introspection entry point; the record hot path uses the raw
    :func:`_leaf_key` form and formats lazily."""
    from jax import tree_util

    leaves, treedef = tree_util.tree_flatten(args)
    return treedef, tuple(format_leaf(_leaf_key(x)) for x in leaves)


def signature_diff(old: Optional[Sequence[str]], new: Sequence[str],
                   old_donate: Sequence[int] = (),
                   new_donate: Sequence[int] = (),
                   max_changed: int = 8) -> Optional[dict]:
    """The "why did this retrace" attribution: a JSON-safe diff of two
    leaf-signature tuples (plus the donation maps), ``None`` for a
    first compile.  ``kinds`` names what moved — ``"dtype"``,
    ``"shape"``, ``"sharding"``, ``"type"`` (array ↔ scalar),
    ``"structure"`` (leaf count or treedef changed), ``"donation"``
    — and ``changed`` lists the first
    ``max_changed`` per-leaf transitions so a /programz reader sees
    the offending leaf, not just a count."""
    if old is None:
        return None
    kinds = set()
    changed: List[dict] = []
    n_changed = 0
    if len(old) != len(new):
        kinds.add("structure")
    for i, (a, b) in enumerate(zip(old, new)):
        if a == b:
            continue
        n_changed += 1
        da, db = a.split("[", 1)[0], b.split("[", 1)[0]
        if a.startswith("py:") or b.startswith("py:"):
            # a python-scalar leaf changed type (py:int → py:float),
            # or an array swapped with a scalar — either way the
            # attribution is "type", never an array-dtype hunt
            kind = "type"
        elif da != db:
            kind = "dtype"
        elif a.split("]", 1)[0] != b.split("]", 1)[0]:
            kind = "shape"
        else:
            # same dtype, same dims: only the @sharding suffix moved
            kind = "sharding"
        kinds.add(kind)
        if len(changed) < max_changed:
            changed.append({"leaf": i, "from": a, "to": b,
                            "kind": kind})
    if tuple(old_donate) != tuple(new_donate):
        kinds.add("donation")
    return {
        "n_old": len(old),
        "n_new": len(new),
        "n_changed": n_changed,
        "kinds": sorted(kinds),
        "changed": changed,
        **({} if tuple(old_donate) == tuple(new_donate)
           else {"donate_from": list(old_donate),
                 "donate_to": list(new_donate)}),
    }


# ---------------------------------------------------------------------- #
# the ledger
# ---------------------------------------------------------------------- #

class ProgramLedger:
    """Bounded ring of compile/retrace events (see module docstring).

    Args:
      capacity: ring length — oldest entries drop when full (the
        per-label seen-sets and counters are NOT ring-bounded; they
        are what keeps a long-running job's hit path a set lookup).
      enabled: start recording immediately (default False — the
        instrumented call sites pay one attribute read and dispatch
        straight through until :meth:`enable`).

    Labels are PROCESS-GLOBAL: every wrapper built with the same
    label shares one signature set.  A REBUILT program (a fresh
    engine after a resize, a second adapter under one ``spec/*``
    label) recompiling an already-seen signature is therefore not
    re-recorded — the ledger answers "did a NEW program shape
    appear", which is the retrace question.  A deliberate rebuild
    that wants its compiles re-attributed calls the SCOPED
    :meth:`forget` (``forget("serve/")`` around an engine rebuild —
    what ``rebind_world`` does for ``train/``): counters stay
    monotonic and other subsystems' label state is untouched, unlike
    the wholesale :meth:`clear`.
    """

    def __init__(self, capacity: int = 1024, enabled: bool = False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        # label -> {"seen": {(treedef, leafsigs)}, "compiles": n,
        #           "calls": n, "steady_compiles": n,
        #           "last_sig": leafsigs, "last_donate": tuple}
        self._labels: Dict[str, dict] = {}
        self._steady: Tuple[str, ...] = ()
        self.total_compile_s = 0.0
        self.dropped = 0
        # the current causal exemplar: a serving engine staging request
        # R sets this to R's trace id, so a compile event caused by R's
        # shapes (the per-(prefix,suffix)-split verify retrace) links
        # its compile/seconds exemplar to R's retained timeline.
        # THREAD-LOCAL: in a colocated train+serve process a training
        # thread's epoch-tail compile must never pick up the serving
        # thread's in-flight request id as its cause
        self._exemplar_local = threading.local()

    @property
    def exemplar(self) -> Optional[str]:
        return getattr(self._exemplar_local, "value", None)

    @exemplar.setter
    def exemplar(self, value: Optional[str]) -> None:
        self._exemplar_local.value = value

    # -- lifecycle ----------------------------------------------------- #

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._labels.clear()
            self._steady = ()
            self.total_compile_s = 0.0
            self.dropped = 0

    def __len__(self) -> int:
        return len(self._ring)

    # -- steady-state declaration -------------------------------------- #

    def mark_steady(self, scope: str) -> None:
        """Declare every label under ``scope`` (a label prefix —
        ``"serve/"``, ``"train/"``) steady-state: the caller asserts
        warmup is over, so any further compile under the scope is a
        RETRACE STORM signal (``compile/steady_retraces``, the
        :func:`retrace_storm_rule` bad feed).  Idempotent."""
        with self._lock:
            if scope not in self._steady:
                self._steady = self._steady + (str(scope),)

    def clear_steady(self, scope: Optional[str] = None) -> None:
        """Withdraw a steady declaration (``None`` withdraws all) —
        the legitimate-recompile escape hatch: a live resize or an
        engine rebuild re-warms, re-marks."""
        with self._lock:
            if scope is None:
                self._steady = ()
            else:
                self._steady = tuple(s for s in self._steady
                                     if s != scope)

    def forget(self, scope: Optional[str] = None) -> None:
        """Drop the SIGNATURE MEMORY for labels under ``scope`` (all
        labels when ``None``) and withdraw the matching steady
        declarations — the REBUILD hook: a re-formed mesh's programs
        (``rebind_world``, a fresh engine after a resize) are new
        executables, so their first calls really re-trace and
        re-compile even at previously-seen signatures, and the ledger
        must re-record them (the post-resize compile lands in the
        ring, the metrics, and the goodput ``compile_s`` badput).
        Counters and ring history are KEPT — only the seen-sets
        clear, so ``compiles()``/``compile_seconds()`` stay
        monotonic; the first post-rebuild entry's signature diff
        reads against the pre-rebuild signature (often "no change" —
        which is itself the attribution: a rebuild, not a shape
        leak)."""
        with self._lock:
            for label, st in self._labels.items():
                if scope is None or label.startswith(scope):
                    st["seen"].clear()
            self._steady = tuple(
                s for s in self._steady
                if not (scope is None or s.startswith(scope)
                        or scope.startswith(s)))

    def is_steady(self, label: str) -> bool:
        return any(label.startswith(s) for s in self._steady)

    # -- recording ----------------------------------------------------- #

    def record_call(self, fn: Callable, label: str,
                    donate: Tuple[int, ...], args: tuple,
                    kwargs: Optional[dict] = None):
        """The instrumented-call hot path: signature lookup, dispatch,
        and — on a first-seen signature — the timed compile record.
        Only :class:`_InstrumentedJit` calls this, and only while
        enabled.  The signature key is raw hashable leaf keys (no
        string work — the <1% bar is won here); the readable form is
        rendered only when a compile is recorded.  Keyword args ride
        the signature through the treedef (a dict pytree keys by
        sorted names, so a kwarg rename is a structure change)."""
        from jax import tree_util

        if kwargs:
            leaves, treedef = tree_util.tree_flatten((args, kwargs))
        else:
            leaves, treedef = tree_util.tree_flatten(args)
            kwargs = {}
        key = (treedef, tuple(_leaf_key(x) for x in leaves))
        with self._lock:
            st = self._labels.get(label)
            if st is None:
                st = self._labels[label] = {
                    "seen": set(), "compiles": 0, "calls": 0,
                    "steady_compiles": 0, "compile_s": 0.0,
                    "last_sig": None, "last_donate": (),
                    "last_treedef": None,
                }
            st["calls"] += 1
            miss = key not in st["seen"]
            if miss:
                # claimed at DETECTION time, under the lock: two
                # threads first-calling the same shape concurrently
                # must record ONE compile, not two (a double-counted
                # steady retrace would feed the storm rule)
                st["seen"].add(key)
        reg = get_registry()
        reg.inc("compile/calls")
        if not miss:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            # the program never materialized — release the claim so
            # a retry's compile is still recorded
            with self._lock:
                self._labels[label]["seen"].discard(key)
            raise
        dt = time.perf_counter() - t0
        self._record_compile(
            label, key, tuple(format_leaf(k) for k in key[1]),
            donate, dt, reg)
        return out

    def _record_compile(self, label, key, leaf_sigs, donate, dt, reg):
        steady = self.is_steady(label)
        treedef = key[0]
        with self._lock:
            st = self._labels[label]
            st["compiles"] += 1
            st["compile_s"] += dt
            if steady:
                st["steady_compiles"] += 1
            diff = signature_diff(st["last_sig"], leaf_sigs,
                                  st["last_donate"], donate)
            # a treedef-only change (dict key rename, container swap —
            # same leaves, different structure) must not render as an
            # empty diff an operator would read as "a rebuild": the
            # structure change IS the retrace cause
            if diff is not None and st.get("last_treedef") is not None \
                    and st["last_treedef"] != treedef \
                    and "structure" not in diff["kinds"]:
                diff["kinds"] = sorted(diff["kinds"] + ["structure"])
            st["last_sig"] = leaf_sigs
            st["last_donate"] = tuple(donate)
            st["last_treedef"] = treedef
            n = st["compiles"]
            self.total_compile_s += dt
            exemplar = self.exemplar
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append({
                "label": label,
                "n": n,
                "compile_s": dt,
                "n_leaves": len(leaf_sigs),
                "signature": list(leaf_sigs[:64]),
                "donate_argnums": list(donate),
                "steady": steady,
                "diff": diff,
                "exemplar": exemplar,
                "ts": time.time(),
            })
        reg.observe("compile/seconds", dt,
                    exemplar=exemplar if exemplar is not None else label)
        reg.inc("compile/retraces")
        reg.inc("compile/retraces_" + _slug(label))
        if steady:
            reg.inc("compile/steady_retraces")
        get_recorder().record(
            f"compile/{label}", dt, cat="compile",
            retrace=n > 1, steady=steady,
            **({} if diff is None else {"diff_kinds": diff["kinds"]}))

    # -- read surface -------------------------------------------------- #

    def entries(self, n: Optional[int] = None,
                scope: Optional[str] = None) -> List[dict]:
        """The newest ``n`` ledger entries (all by default), NEWEST
        FIRST — the incident-reading order — optionally restricted to
        labels under ``scope``."""
        with self._lock:
            rows = list(self._ring)
        if scope is not None:
            rows = [r for r in rows if r["label"].startswith(scope)]
        rows.reverse()
        return rows if n is None or n < 0 else rows[:int(n)]

    def compiles(self, scope: Optional[str] = None) -> int:
        """Total compiles recorded (survives ring wrap), optionally
        restricted to labels under ``scope`` — the number the
        zero-steady-state-recompile tests snapshot and re-read."""
        with self._lock:
            return sum(st["compiles"]
                       for label, st in self._labels.items()
                       if scope is None or label.startswith(scope))

    def steady_retraces(self, scope: Optional[str] = None) -> int:
        with self._lock:
            return sum(st["steady_compiles"]
                       for label, st in self._labels.items()
                       if scope is None or label.startswith(scope))

    def compile_seconds(self, scopes=None) -> float:
        """Cumulative recorded compile wall seconds, optionally
        restricted to labels under any of ``scopes`` (one prefix or a
        tuple of prefixes) — what lets a TRAINING goodput window bill
        only training-side compiles while a colocated serving engine
        compiles its own programs in the same process."""
        if scopes is None:
            return self.total_compile_s
        if isinstance(scopes, str):
            scopes = (scopes,)
        with self._lock:
            return sum(st["compile_s"]
                       for label, st in self._labels.items()
                       if any(label.startswith(s) for s in scopes))

    def label_stats(self) -> Dict[str, dict]:
        """Per-label ``{compiles, calls, steady_compiles, compile_s,
        programs}`` (``programs`` = distinct signatures = live
        executables)."""
        with self._lock:
            return {label: {"compiles": st["compiles"],
                            "calls": st["calls"],
                            "steady_compiles": st["steady_compiles"],
                            "compile_s": st["compile_s"],
                            "programs": len(st["seen"])}
                    for label, st in self._labels.items()}

    def status(self) -> dict:
        """The ``/programz`` summary block (JSON-safe)."""
        stats = self.label_stats()
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "recorded": len(self._ring),
            "dropped": self.dropped,
            "total_compile_s": self.total_compile_s,
            "steady_scopes": list(self._steady),
            "labels": stats,
            "compiles": sum(s["compiles"] for s in stats.values()),
            "steady_retraces": sum(s["steady_compiles"]
                                   for s in stats.values()),
        }


# ---------------------------------------------------------------------- #
# instrumentation wrappers
# ---------------------------------------------------------------------- #

class _InstrumentedJit:
    """The cache-miss hook around one jitted callable.  Disabled
    ledger: one attribute read, then straight dispatch.  Attribute
    access (``.lower``, ``._cache_size`` — the HLO-proof surfaces the
    optimizer tests drive) delegates to the wrapped jit function."""

    __slots__ = ("_fn", "label", "donate")

    def __init__(self, fn: Callable, label: str,
                 donate: Sequence[int] = ()):
        self._fn = fn
        self.label = str(label)
        self.donate = tuple(int(i) for i in donate)

    def __call__(self, *args, **kwargs):
        led = _GLOBAL
        if not led.enabled:
            return self._fn(*args, **kwargs)
        return led.record_call(self._fn, self.label, self.donate,
                               args, kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"<instrumented jit {self.label!r}>"


def instrument(fn: Callable, label: str,
               donate_argnums: Sequence[int] = ()) -> _InstrumentedJit:
    """Wrap an already-jitted callable with the ledger's cache-miss
    hook.  The wrapper resolves the GLOBAL ledger per call, so
    :func:`set_ledger` swaps (tests, scoped benches) are honored."""
    return _InstrumentedJit(fn, label, donate_argnums)


def ledger_jit(fun: Callable, *, label: str, **jit_kwargs):
    """``jax.jit`` + :func:`instrument` in one call — the drop-in form
    for the stack's jit call sites (``ledger_jit(body, label=
    "serve/round", donate_argnums=(1, 2))``).  All keyword arguments
    besides ``label`` pass through to ``jax.jit``; the donation map
    rides the ledger entries."""
    import jax

    donate = jit_kwargs.get("donate_argnums", ())
    if isinstance(donate, int):
        donate = (donate,)
    return instrument(jax.jit(fun, **jit_kwargs), label, donate)


# ---------------------------------------------------------------------- #
# the device-memory accountant
# ---------------------------------------------------------------------- #

def _leaf_bytes(x) -> int:
    """Device bytes held by one leaf.  A sharded jax array counts its
    ADDRESSABLE SHARDS (replication is real memory — an 8-device
    replicated array holds 8 copies); anything else with ``nbytes``
    counts that; the rest count zero."""
    shards = getattr(x, "addressable_shards", None)
    if shards is not None:
        try:
            return int(sum(s.data.nbytes for s in shards))
        except Exception:       # noqa: BLE001 — a deleted/donated array
            return 0
    nb = getattr(x, "nbytes", None)
    try:
        return int(nb) if nb is not None else 0
    except (TypeError, ValueError):
        return 0


def weakref_root(obj, *attrs) -> Callable[[], Optional[list]]:
    """A zero-arg accountant root reading ``[obj.a for a in attrs]``
    through a WEAK reference — the one place the dead-root contract
    lives: registration never pins a retired owner, and once the
    owner is collected the root resolves to ``None`` (samples as 0
    bytes).  ``ServingEngine.register_memory`` and
    ``StandardUpdater.register_memory`` both register through this."""
    import weakref

    ref = weakref.ref(obj)

    def read():
        owner = ref()
        return None if owner is None else [getattr(owner, a)
                                           for a in attrs]

    return read


def tree_bytes(root) -> int:
    """Total device bytes across a pytree of arrays (jax resolves
    lazily; a non-tree leaf counts via its own ``nbytes``)."""
    try:
        from jax import tree_util

        leaves = tree_util.tree_leaves(root)
    except Exception:           # noqa: BLE001 — jax-free tooling
        leaves = root if isinstance(root, (list, tuple)) else [root]
    return sum(_leaf_bytes(x) for x in leaves)


class MemoryAccountant:
    """Per-subsystem live-buffer byte gauges with high-watermarks.

    Subsystems register the ROOTS of what they keep alive on device —
    a pytree, or (the usual form) a zero-arg callable re-resolved per
    sample, so a root that is reassigned (a donated carry, a reset
    engine) is never sampled stale.  :meth:`sample` walks every root
    into ``memory/<subsystem>_bytes`` gauges plus ``memory/
    total_bytes``; the gauge's ``max`` is the high-watermark, and the
    accountant keeps its own watermark table too so ``/programz``
    renders one with the registry disabled."""

    def __init__(self):
        self._roots: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._last: Dict[str, int] = {}
        self._watermark: Dict[str, int] = {}
        self._errors: Dict[str, str] = {}
        self._lock = threading.Lock()

    def register(self, name: str, root) -> "MemoryAccountant":
        """Register (or replace) subsystem ``name``'s buffer root —
        a pytree or a zero-arg callable returning one."""
        with self._lock:
            self._roots[str(name)] = root
        return self

    def unregister(self, name: str) -> None:
        with self._lock:
            self._roots.pop(str(name), None)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._roots)

    def sample(self, registry=None) -> Dict[str, int]:
        """Walk every registered root into per-subsystem byte totals;
        set the gauges; return ``{subsystem: bytes}``.  A root whose
        callable raises samples as 0 (accounting must never kill the
        loop) — the error string lands in the /programz table."""
        with self._lock:
            roots = list(self._roots.items())
        out: Dict[str, int] = {}
        errors: Dict[str, str] = {}
        for name, root in roots:
            try:
                tree = root() if callable(root) else root
                out[name] = tree_bytes(tree)
            except Exception as err:    # noqa: BLE001
                out[name] = 0
                errors[name] = f"{type(err).__name__}: {err}"
        total = sum(out.values())
        with self._lock:
            self._last = dict(out)
            self._errors = errors
            for name, b in out.items():
                if b > self._watermark.get(name, -1):
                    self._watermark[name] = b
            if total > self._watermark.get("total", -1):
                self._watermark["total"] = total
        if registry is None:
            registry = get_registry()
        for name, b in out.items():
            registry.set(f"memory/{_slug(name)}_bytes", b)
        registry.set("memory/total_bytes", total)
        return out

    def table(self) -> List[dict]:
        """The ``/programz`` memory rows: one per subsystem —
        last-sampled bytes and the high-watermark."""
        with self._lock:
            errors = self._errors
            rows = [{"subsystem": name,
                     "bytes": self._last.get(name),
                     "high_watermark": self._watermark.get(name),
                     **({"error": errors[name]} if name in errors
                        else {})}
                    for name in self._roots]
            rows.append({"subsystem": "total",
                         "bytes": (sum(self._last.values())
                                   if self._last else None),
                         "high_watermark": self._watermark.get("total")})
        return rows

    def clear(self) -> None:
        with self._lock:
            self._roots.clear()
            self._last.clear()
            self._watermark.clear()
            self._errors.clear()


# ---------------------------------------------------------------------- #
# the retrace-storm alert rule
# ---------------------------------------------------------------------- #

def retrace_storm_rule(name: str = "retrace-storm", *,
                       budget: float = 0.001,
                       windows=None, protect: bool = False):
    """A burn-rate rule over the ledger's counters: bad =
    ``compile/steady_retraces`` (compiles after a phase was declared
    steady), total = ``compile/calls``.  A healthy steady phase
    compiles NOTHING, so the sustainable bad fraction is ~0 and any
    sustained recompile churn (a shape leak in the serving round, an
    un-cached tail shape every epoch) burns the budget within one
    window pair.  ``protect`` defaults False — a retrace storm wants a
    page and a /programz read, not admission shedding."""
    from chainermn_tpu.utils.alerts import DEFAULT_WINDOWS, RatioRule

    return RatioRule(
        name,
        bad="compile/steady_retraces",
        total="compile/calls",
        budget=budget,
        windows=DEFAULT_WINDOWS if windows is None else windows,
        protect=protect,
    )


# ---------------------------------------------------------------------- #
# process-global instances
# ---------------------------------------------------------------------- #

def _from_env() -> ProgramLedger:
    enabled = os.environ.get("CHAINERMN_TPU_PROGRAMS", "") \
        not in ("", "0")
    try:
        capacity = int(os.environ.get(
            "CHAINERMN_TPU_PROGRAMS_CAPACITY", 1024))
        if capacity < 1:
            raise ValueError(capacity)
    except ValueError:
        capacity = 1024     # typo'd env degrades, never crashes import
    return ProgramLedger(capacity=capacity, enabled=enabled)


_GLOBAL = _from_env()
_ACCOUNTANT = MemoryAccountant()


def get_ledger() -> ProgramLedger:
    """The process-global program ledger every instrumented jit call
    site records into (disabled by default — see module docstring)."""
    return _GLOBAL


def set_ledger(ledger: ProgramLedger) -> ProgramLedger:
    """Swap the global ledger (tests, scoped benches); returns the
    previous one so callers can restore it."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = ledger
    return prev


def get_accountant() -> MemoryAccountant:
    """The process-global memory accountant (always constructed; a
    sample with nothing registered is an empty table)."""
    return _ACCOUNTANT


def set_accountant(acc: MemoryAccountant) -> MemoryAccountant:
    global _ACCOUNTANT
    prev = _ACCOUNTANT
    _ACCOUNTANT = acc
    return prev
