"""Flight recorder — structured span tracing for the whole training stack.

The Profiler (:mod:`chainermn_tpu.utils.profiling`) answers *"how much
time does phase X cost on average"*; it is a flat name→stats table with
no ordering, no per-event timestamps, and no cross-rank story.  The
ROADMAP's next levers (backward-overlapped exchange, elastic training)
need the question it cannot answer: *"what was each rank doing, when,
overlapped with what"* — a timeline.  HiCCL and the overlapping-
allreduce literature (PAPERS.md 2408.05962 / 2508.13397) both assume
exactly this per-collective, per-phase telemetry; SURVEY §5 names it as
the capability the reference out-sourced to external tracers.

Three layers:

- :class:`TraceRecorder` — a bounded ring buffer of structured span
  events (name, category, t0/duration, step, rank, thread, metadata).
  Near-zero cost when disabled: ``span()`` returns a shared no-op
  context manager (no allocation, one attribute read).  Exports:

  * **Chrome trace-event JSON** (:meth:`export_chrome`) — load the file
    at https://ui.perfetto.dev (or ``chrome://tracing``).  Ranks map to
    pids, threads to tids, so a merged multi-process trace renders as
    one timeline with a lane per rank; :func:`merge_traces` fuses
    per-rank shard files into that single document.
  * **streaming JSONL** (``stream_path=``) — every completed event is
    appended as one JSON line the moment it retires, so a SIGKILL'd
    process still leaves its timeline on disk up to the kill point
    (:meth:`export_jsonl` dumps the ring after the fact).

- :class:`StragglerReport` — a trainer extension that allgathers each
  process's per-phase mean durations and reports, per phase, the
  slowest rank and the skew ratio (slowest / mean) —
  ``main/straggler_skew`` is the max skew over phases.  This is the
  cross-rank attribution the overlap work needs before it can claim a
  win: "step time is X" becomes "rank 3's host phase is 2.1× the mean".

- :class:`MetricsExport` — a JSONL time-series appender for
  ``trainer.observation``: one line per trigger with iteration, epoch,
  wall clock and every float-valued observation, flushed per line so a
  crash keeps the series.

Failure-path integration (wired in the respective modules): the
:class:`~chainermn_tpu.extensions.TrainingWatchdog` stall report embeds
the recorder's ring tail (``trace_tail``), and
:func:`~chainermn_tpu.extensions.add_global_except_hook` dumps the
trace next to the crash — post-mortems come with a timeline of the
seconds before death, not just stacks.

The global recorder starts DISABLED.  Enable explicitly
(``get_recorder().enable()``), or set ``CHAINERMN_TPU_TRACE=1`` in the
environment (optionally ``CHAINERMN_TPU_TRACE_CAPACITY`` /
``CHAINERMN_TPU_TRACE_STREAM=<path>``) before import.  See
docs/OBSERVABILITY.md for the Perfetto workflow.

This module must stay importable without jax (the rank lookup is lazy):
it is imported by the iterator/prefetch layer, which keeps its imports
light.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from chainermn_tpu.utils.metrics import Histogram, append_jsonl

__all__ = [
    "MetricsExport",
    "RequestTraceStore",
    "SpanEvent",
    "StragglerReport",
    "TraceRecorder",
    "get_recorder",
    "merge_traces",
    "set_recorder",
]

# Chrome trace-event phase codes used here: "X" complete (span with
# duration), "i" instant, "C" counter, "M" metadata.
_PH_SPAN, _PH_INSTANT, _PH_COUNTER = "X", "i", "C"


def _default_rank() -> int:
    """The process rank for the pid mapping — lazy so the module imports
    without jax (and before distributed init)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class SpanEvent:
    """One recorded event.  ``dur`` is seconds for spans, ``None`` for
    instants, and carries the counter value for counter events."""

    __slots__ = ("name", "cat", "ph", "t0", "dur", "step", "tid", "meta")

    def __init__(self, name, cat, ph, t0, dur, step, tid, meta):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.t0 = t0
        self.dur = dur
        self.step = step
        self.tid = tid
        self.meta = meta

    def to_dict(self) -> dict:
        d = {"name": self.name, "cat": self.cat, "ph": self.ph,
             "t0": self.t0}
        if self.dur is not None:
            d["dur"] = self.dur
        if self.step is not None:
            d["step"] = self.step
        if self.tid is not None:
            d["tid"] = self.tid
        if self.meta:
            d["meta"] = self.meta
        return d


class _NullSpan:
    """The disabled-path context manager: ONE shared instance, so a
    disabled recorder allocates nothing per span (pinned by test)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **meta):
        return self


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("_rec", "_name", "_cat", "_step", "_meta", "_t0")

    def __init__(self, rec, name, cat, step, meta):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._step = step
        self._meta = meta

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def set(self, **meta):
        """Attach metadata discovered inside the block (measured values,
        outcome flags); merged into the event on exit."""
        if self._meta is None:
            self._meta = meta
        else:
            self._meta.update(meta)
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._rec._append(SpanEvent(
            self._name, self._cat, _PH_SPAN, self._t0, t1 - self._t0,
            self._step, threading.get_ident(), self._meta))
        return False


class TraceRecorder:
    """Bounded flight recorder of structured span events.

    Args:
      capacity: ring length — oldest events drop when full.  65536
        events ≈ a few MB; at ~6 spans per training step that is hours
        of history.
      enabled: start recording immediately (default False — the
        instrumented hot paths pay one attribute read and nothing else
        until :meth:`enable` is called).
      rank: the pid this recorder's events map to in the Chrome export.
        Default: ``jax.process_index()`` resolved lazily at export
        time, so construction never touches jax.
      stream_path: when set, every completed event is ALSO appended to
        this file as one JSON line at record time (crash-durable
        streaming export; the ring is unaffected).

    Thread-safe: spans may open/close on any thread (the prefetch
    worker, checkpoint writer and watchdog monitor all record); the
    thread id rides each event and becomes the Chrome tid.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = False,
                 rank: Optional[int] = None,
                 stream_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._rank = rank
        self.stream_path = stream_path
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._stream_file = None
        # phase-stats accumulators: one independent CHANNEL per
        # consumer, stored as [name_filter_or_None, {name: [n, tot, mx,
        # Histogram]}]; the default "" channel (no filter) feeds
        # StragglerReport, and open_phase_channel() gives other
        # consumers (GoodputReport) their own interval state so a drain
        # on one never steals another's feed
        self._phase_channels: Dict[str, list] = {"": [None, {}]}
        self._thread_names: Dict[int, str] = {}
        # wall-clock anchor: perf_counter is monotonic but arbitrary;
        # the pair lets exports (and merge across processes) place
        # events on the wall clock
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()
        self.dropped = 0          # events displaced by ring wrap

    # ------------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------------ #

    @property
    def rank(self) -> int:
        if self._rank is None:
            self._rank = _default_rank()
        return self._rank

    @rank.setter
    def rank(self, value: int) -> None:
        self._rank = int(value)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def __len__(self) -> int:
        return len(self._ring)

    def span(self, name: str, cat: str = "default",
             step: Optional[int] = None, **meta):
        """Context manager timing a block into the ring.  Disabled →
        returns the shared no-op singleton (zero allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, cat, step, meta or None)

    def record(self, name: str, duration: float, cat: str = "default",
               step: Optional[int] = None, t0: Optional[float] = None,
               **meta) -> None:
        """Record an already-measured span (duration seconds; ``t0`` on
        the ``time.perf_counter`` clock, default now-minus-duration)."""
        if not self.enabled:
            return
        if t0 is None:
            t0 = time.perf_counter() - duration
        self._append(SpanEvent(name, cat, _PH_SPAN, t0, float(duration),
                               step, threading.get_ident(), meta or None))

    def instant(self, name: str, cat: str = "default",
                step: Optional[int] = None, **meta) -> None:
        """Zero-duration marker (heartbeats, plan changes, faults)."""
        if not self.enabled:
            return
        self._append(SpanEvent(name, cat, _PH_INSTANT,
                               time.perf_counter(), None, step,
                               threading.get_ident(), meta or None))

    def counter(self, name: str, value: float, cat: str = "counter",
                step: Optional[int] = None) -> None:
        """Sampled value rendered as a counter track in Perfetto
        (prefetch occupancy, queue depths)."""
        if not self.enabled:
            return
        self._append(SpanEvent(name, cat, _PH_COUNTER,
                               time.perf_counter(), float(value), step,
                               threading.get_ident(), None))

    def _append(self, ev: SpanEvent) -> None:
        tid = ev.tid
        if tid is not None and tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(ev)      # deque.append is atomic
        if ev.ph == _PH_SPAN:
            with self._lock:
                for flt, accs in self._phase_channels.values():
                    if flt is not None and ev.name not in flt:
                        continue
                    acc = accs.get(ev.name)
                    if acc is None:
                        # the histogram rides the shared metrics
                        # lattice, so StragglerReport's cross-rank
                        # merge is a bucket sum
                        acc = accs[ev.name] = [0, 0.0, ev.dur,
                                               Histogram()]
                    acc[0] += 1
                    acc[1] += ev.dur
                    acc[2] = max(acc[2], ev.dur)
                    acc[3].observe(ev.dur)
        if self.stream_path is not None:
            self._stream(ev)

    def _stream(self, ev: SpanEvent) -> None:
        with self._lock:
            if self.stream_path is None:    # closed under our feet
                return
            try:
                if self._stream_file is None:
                    self._stream_file = open(self.stream_path, "a")
                self._stream_file.write(
                    json.dumps(ev.to_dict(), default=str) + "\n")
                self._stream_file.flush()
            except OSError:
                # a full disk must degrade the stream, never training
                if self._stream_file is not None:
                    try:
                        self._stream_file.close()
                    except OSError:
                        pass
                self.stream_path = None
                self._stream_file = None

    def clear(self) -> None:
        self._ring.clear()
        with self._lock:
            for chan in self._phase_channels.values():
                chan[1].clear()
        self.dropped = 0

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def tail(self, n: int = 64) -> List[dict]:
        """The newest ``n`` events as JSON-safe dicts — what the
        watchdog embeds in a stall report and the except hook dumps on
        crash: the timeline of the seconds before things went wrong.
        ``n <= 0`` means none (the opt-out, not the whole ring)."""
        if n <= 0:
            return []
        return [ev.to_dict() for ev in list(self._ring)[-n:]]

    def events(self) -> List[dict]:
        # list(deque) is a C-atomic snapshot: concurrent appends from
        # other threads (prefetch worker, watchdog monitor) must never
        # fault an export with "deque mutated during iteration"
        return [ev.to_dict() for ev in list(self._ring)]

    def open_phase_channel(self, key: str,
                           names: Optional[Sequence[str]] = None
                           ) -> str:
        """Register an INDEPENDENT phase-stats accumulator.  A channel
        sees every span recorded after it opens (restricted to
        ``names`` when given — a consumer with a fixed name list should
        pass it, so the channel neither pays accumulation cost nor
        retains histograms for spans it will never drain); draining one
        channel never touches another, so interval consumers with
        overlapping name sets (``StragglerReport`` on the default
        channel, ``GoodputReport`` on its own) each get the full feed.
        Idempotent for the same arguments (re-opening replaces the
        filter); returns ``key``."""
        flt = None if names is None else frozenset(names)
        with self._lock:
            chan = self._phase_channels.get(key)
            if chan is None:
                self._phase_channels[key] = [flt, {}]
            else:
                chan[0] = flt
        return key

    def drain_phase_stats(self, names: Optional[Sequence[str]] = None,
                          channel: str = "") -> Dict[str, dict]:
        """Per-span-name ``{count, total_s, max_s, hist}`` accumulated
        on ``channel`` since its last drain, then reset (``hist`` is a
        duration :class:`~chainermn_tpu.utils.metrics.Histogram`
        snapshot on the shared lattice — the per-phase distribution
        behind :class:`StragglerReport`'s tail percentiles).  Survives
        ring wrap (accumulated at record time), so interval statistics
        stay exact however small the ring.

        ``names`` drains ONLY those span names, leaving the rest
        accumulating; ``channel`` selects which consumer's accumulator
        to drain (default: the shared one ``StragglerReport`` uses).
        An unknown channel raises — :meth:`open_phase_channel` is the
        one registration point, and a typo'd key silently returning
        ``{}`` forever is exactly the bug that must not ship."""
        with self._lock:
            chan = self._phase_channels.get(channel)
            if chan is None:
                raise KeyError(
                    f"unknown phase channel {channel!r} — call "
                    f"open_phase_channel first (open: "
                    f"{sorted(self._phase_channels)})")
            accs = chan[1]
            if names is None:
                drained = dict(accs)
                accs.clear()
            else:
                drained = {}
                for name in names:
                    acc = accs.pop(name, None)
                    if acc is not None:
                        drained[name] = acc
        return {name: {"count": a[0], "total_s": a[1], "max_s": a[2],
                       "hist": a[3].to_snapshot()}
                for name, a in drained.items()}

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #

    def _ts_us(self, t0: float) -> float:
        """perf_counter → wall-clock microseconds (the Chrome ``ts``
        axis; wall-anchored so independently-exported per-rank shards
        land on one comparable timeline, modulo host clock skew)."""
        return (t0 - self._anchor_perf + self._anchor_wall) * 1e6

    def chrome_events(self) -> List[dict]:
        """The ring as Chrome trace-event dicts (rank → pid, thread →
        tid), prefixed with the process/thread-name metadata events
        Perfetto uses to label the lanes."""
        pid = self.rank
        events: List[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": f"rank {pid}"},
        }]
        ring = list(self._ring)     # atomic snapshot (see events())
        tids = sorted({ev.tid for ev in ring if ev.tid is not None})
        tid_map = {ident: i for i, ident in enumerate(tids)}
        for ident in tids:
            events.append({
                "ph": "M", "pid": pid, "tid": tid_map[ident],
                "name": "thread_name",
                "args": {"name": self._thread_names.get(
                    ident, f"thread-{ident}")},
            })
        for ev in ring:
            rec = {
                "name": ev.name,
                "cat": ev.cat,
                "ph": ev.ph,
                "pid": pid,
                "tid": tid_map.get(ev.tid, 0),
                "ts": self._ts_us(ev.t0),
            }
            if ev.ph == _PH_SPAN:
                rec["dur"] = ev.dur * 1e6
            args = dict(ev.meta) if ev.meta else {}
            if ev.step is not None:
                args["step"] = ev.step
            if ev.ph == _PH_COUNTER:
                args["value"] = ev.dur
            if args:
                rec["args"] = args
            events.append(rec)
        return events

    def export_chrome(self, path: str) -> str:
        """Write the Perfetto-loadable Chrome trace JSON document."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "metadata": {
                "rank": self.rank,
                "capacity": self.capacity,
                "dropped": self.dropped,
                "anchor_wall_s": self._anchor_wall,
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        return path

    def export_jsonl(self, path: str) -> str:
        """Dump the ring as JSON lines (one event per line) — the
        after-the-fact form of the ``stream_path`` live export."""
        with open(path, "w") as f:
            for ev in list(self._ring):     # atomic snapshot
                f.write(json.dumps(ev.to_dict(), default=str) + "\n")
        return path

    def close(self) -> None:
        """End the streaming export: close the file AND clear
        ``stream_path``, so a straggler thread recording afterwards
        (prefetch worker, watchdog monitor) cannot silently reopen the
        file a reader already treated as end-of-stream."""
        with self._lock:
            self.stream_path = None
            if self._stream_file is not None:
                try:
                    self._stream_file.close()
                except OSError:
                    pass
                self._stream_file = None


def merge_traces(paths, out: Optional[str] = None) -> dict:
    """Fuse per-rank Chrome trace shards into ONE Perfetto document.

    ``paths`` may be a sequence of shard files, a DIRECTORY (every
    ``*.json`` inside), or a GLOB pattern (``"traces/rank*.json"``).
    However they arrive, shards are sorted deterministically by their
    recorded rank (``metadata.rank``; rankless shards sort after, by
    file name) BEFORE pid assignment — so the same shard set always
    produces the same Perfetto pid lanes, regardless of listing order
    (callers used to have to pre-sort paths themselves to keep pids
    stable across merges).

    Each shard keeps its own pid lane (rank → pid).  If two shards
    claim the same pid — e.g. single-process drills exporting twice —
    the later shard's pids are shifted past every pid already taken,
    so lanes never silently overlay.  Events merge in shard order;
    Perfetto sorts by ``ts`` itself (shards are wall-clock anchored).

    Returns the merged document; writes it to ``out`` when given.
    """
    import glob as _glob

    if isinstance(paths, (str, os.PathLike)):
        root = os.fspath(paths)
        if os.path.isdir(root):
            paths = [os.path.join(root, f) for f in os.listdir(root)
                     if f.endswith(".json")]
        else:
            paths = _glob.glob(root)
        if not paths:
            # a typo'd glob or empty/missing directory must not
            # succeed with an empty Perfetto doc (an explicit path
            # list still raises at open(), as it always did)
            raise FileNotFoundError(
                f"merge_traces: no trace shards found at {root!r}")

    shards: List[tuple] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        rank = (doc.get("metadata", {}).get("rank")
                if isinstance(doc, dict) else None)
        shards.append((path, rank, doc))
    shards.sort(key=lambda s: (s[1] is None,
                               s[1] if isinstance(s[1], int) else 0,
                               os.path.basename(s[0])))

    merged: List[dict] = []
    meta: List[dict] = []
    used_pids: set = set()
    for path, rank, doc in shards:
        # both standard Chrome forms: object with traceEvents, or a
        # bare event array
        events = (doc.get("traceEvents", []) if isinstance(doc, dict)
                  else doc if isinstance(doc, list) else [])
        shard_pids = {ev.get("pid", 0) for ev in events}
        shift = 0
        if shard_pids & used_pids:
            shift = (max(used_pids) + 1) - min(shard_pids)
        used_pids |= {p + shift for p in shard_pids}
        for ev in events:
            if shift:
                ev = dict(ev)
                ev["pid"] = ev.get("pid", 0) + shift
            merged.append(ev)
        meta.append({"path": os.path.basename(path),
                     "pid_shift": shift,
                     **({} if rank is None else {"rank": rank})})
    doc = {"traceEvents": merged, "displayTimeUnit": "ms",
           "metadata": {"merged_from": meta}}
    if out is not None:
        with open(out, "w") as f:
            json.dump(doc, f, default=str)
    return doc


# ---------------------------------------------------------------------- #
# per-request causal traces
# ---------------------------------------------------------------------- #

class RequestTraceStore:
    """Tail-based retention of per-request causal traces.

    The flight recorder's ring answers *"what was this process doing"*;
    a serving operator's question is *"what happened to THIS request"*.
    The engine assembles one span timeline per request (``queue_wait``,
    ``admit``, ``prefill``/``chunk_prefill``, sampled ``decode_round``\\ s,
    the terminal ``evict``/``shed``) and OFFERS the finished trace
    here.  Retention is tail-based — the retention the exemplar link
    needs, because exemplars point at tails:

    - any non-``"ok"`` terminal status (shed / timeout / cancelled /
      quarantined) is ALWAYS kept;
    - an ok request that violated its end-to-end SLO target
      (``slo_e2e``) is ALWAYS kept;
    - remaining ok requests are kept at ``sample_rate``, decided
      DETERMINISTICALLY from the trace id (crc32 hash — the same
      request keeps or drops identically on every rank and replay).

    Capacity-bounded (oldest retained trace drops first), thread-safe,
    and exportable: :meth:`to_chrome` renders retained traces as a
    Chrome/Perfetto document on the same wall-anchored timeline as
    :meth:`TraceRecorder.export_chrome`, so :func:`merge_traces` fuses
    request lanes with the process timeline.  ``/tracez``
    (:mod:`chainermn_tpu.utils.statusz`) serves :meth:`traces` live.
    """

    def __init__(self, capacity: int = 256, sample_rate: float = 0.0,
                 slo_e2e: Optional[float] = None,
                 rank: Optional[int] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate={sample_rate} not in [0, 1]")
        self.capacity = int(capacity)
        self.sample_rate = float(sample_rate)
        self.slo_e2e = slo_e2e
        self._rank = rank
        self._traces: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.offered = 0
        self.kept = 0
        # wall anchor for Chrome export (the TraceRecorder convention:
        # span t0 is on the perf_counter clock, exports are wall-based)
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()

    def __len__(self) -> int:
        return len(self._traces)

    @property
    def rank(self) -> int:
        if self._rank is None:
            self._rank = _default_rank()
        return self._rank

    def would_sample(self, trace_id: str) -> bool:
        """The deterministic ok-path sampling decision for
        ``trace_id`` (hash-based, not RNG-based — replayable)."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        import zlib

        h = zlib.crc32(str(trace_id).encode()) % 1_000_000
        return h / 1_000_000.0 < self.sample_rate

    def offer(self, trace: dict) -> bool:
        """Offer a finished request trace ``{"trace_id", "rid",
        "status", "spans": [{"name", "t0", "dur", ...}], ...}``;
        returns whether it was retained.  The tail-based verdict and
        its inputs are stamped onto the trace (``slo_violated``,
        ``sampled``) so a reader knows WHY a trace is present."""
        status = trace.get("status", "ok")
        e2e = trace.get("e2e")
        violated = bool(self.slo_e2e is not None and e2e is not None
                        and e2e > self.slo_e2e)
        trace["slo_violated"] = violated
        keep = status != "ok" or violated
        if not keep:
            keep = self.would_sample(trace.get("trace_id", ""))
            trace["sampled"] = keep
        if not keep:
            with self._lock:
                self.offered += 1
            return False
        with self._lock:
            # the retention counters share the lock with the dict:
            # two engines may offer into one store concurrently
            self.offered += 1
            self.kept += 1
            self._traces[str(trace.get("trace_id"))] = trace
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
        return True

    def get(self, trace_id: str) -> Optional[dict]:
        """The retained trace for ``trace_id`` (``None`` if it was
        dropped, sampled out, or never offered) — the resolution step
        of the exemplar link: histogram p99 → exemplar trace id →
        this."""
        with self._lock:
            return self._traces.get(str(trace_id))

    def traces(self, n: Optional[int] = None) -> List[dict]:
        """The newest ``n`` retained traces (all by default), oldest
        first.  A negative ``n`` reads as "all" — never the
        everything-BUT-the-oldest slice ``vals[-n:]`` would give."""
        with self._lock:
            vals = list(self._traces.values())
        if n is None or int(n) < 0:
            return vals
        return vals[len(vals) - min(int(n), len(vals)):]

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def snapshot(self) -> dict:
        """Retention counters for ``/statusz``."""
        return {
            "capacity": self.capacity,
            "sample_rate": self.sample_rate,
            "slo_e2e": self.slo_e2e,
            "offered": self.offered,
            "kept": self.kept,
            "retained": len(self._traces),
        }

    # -- export -------------------------------------------------------- #

    def _ts_us(self, t0: float) -> float:
        return (t0 - self._anchor_perf + self._anchor_wall) * 1e6

    def to_chrome(self, trace_id: Optional[str] = None) -> dict:
        """Retained traces (or just ``trace_id``) as a Chrome
        trace-event document: pid = rank (the process's lane, same as
        the TraceRecorder export), one tid LANE PER REQUEST labelled
        with its rid/trace id, spans wall-anchored — feed it to
        :func:`merge_traces` next to the recorder shards and the
        request rows line up under the engine timeline."""
        pid = self.rank
        with self._lock:
            if trace_id is not None:
                # an exemplar can outlive its trace (capacity
                # eviction) — the export degrades to an empty
                # document, the get()-returns-None contract
                tr = self._traces.get(str(trace_id))
                rows = [] if tr is None else [tr]
            else:
                rows = list(self._traces.values())
        events: List[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": f"rank {pid} requests"},
        }]
        for tid, tr in enumerate(rows, start=1):
            events.append({
                "ph": "M", "pid": pid, "tid": tid,
                "name": "thread_name",
                "args": {"name": f"req {tr.get('rid')} "
                                 f"[{tr.get('trace_id')}]"},
            })
            for span in tr.get("spans", ()):
                rec = {
                    "name": span["name"],
                    "cat": "request",
                    "ph": _PH_SPAN,
                    "pid": pid,
                    "tid": tid,
                    "ts": self._ts_us(span["t0"]),
                    "dur": float(span.get("dur", 0.0)) * 1e6,
                }
                args = {k: v for k, v in span.items()
                        if k not in ("name", "t0", "dur")}
                args["trace_id"] = tr.get("trace_id")
                rec["args"] = args
                events.append(rec)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"rank": pid, "request_traces": len(rows)},
        }

    def export_chrome(self, path: str,
                      trace_id: Optional[str] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome(trace_id), f, default=str)
        return path


# ---------------------------------------------------------------------- #
# global recorder
# ---------------------------------------------------------------------- #

def _from_env() -> TraceRecorder:
    enabled = os.environ.get("CHAINERMN_TPU_TRACE", "") not in ("", "0")
    try:
        capacity = int(os.environ.get(
            "CHAINERMN_TPU_TRACE_CAPACITY", 65536))
        if capacity < 1:
            raise ValueError(capacity)
    except ValueError:
        # observability must never kill training: a typo'd env var
        # (runs at package import) degrades to the default, not a crash
        capacity = 65536
    stream = os.environ.get("CHAINERMN_TPU_TRACE_STREAM") or None
    return TraceRecorder(capacity=capacity, enabled=enabled,
                         stream_path=stream)


_GLOBAL = _from_env()


def get_recorder() -> TraceRecorder:
    """The process-global flight recorder every instrumented subsystem
    records into (disabled by default — see module docstring)."""
    return _GLOBAL


def set_recorder(recorder: TraceRecorder) -> TraceRecorder:
    """Swap the global recorder (tests, custom capacities); returns the
    previous one so callers can restore it."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = recorder
    return prev


# ---------------------------------------------------------------------- #
# trainer extensions
# ---------------------------------------------------------------------- #

class StragglerReport:
    """Cross-rank straggler attribution from the flight recorder.

    On each trigger: drain this process's per-phase duration stats
    accumulated since the last fire, ``allgather_obj`` them, and for
    every phase any rank reported compute the mean-of-means, the
    slowest rank, and the skew ratio (slowest rank's mean / cross-rank
    mean; 1.0 = perfectly balanced) — plus, because the drained stats
    carry per-phase duration histograms on the shared metrics lattice
    (:mod:`chainermn_tpu.utils.metrics`), the MERGED cross-rank p50
    and p99 per phase and a tail-skew attribution (``slowest_rank_p99``
    / ``skew_p99``): stragglers live in tails, which a mean hides.
    Processes may report divergent
    phase sets (rank-0-only extensions, mid-epoch joins) — each phase
    aggregates over the ranks that actually reported it, the
    :class:`~chainermn_tpu.extensions.ObservationAggregator`
    convention.

    Observes ``main/straggler_skew`` — the max skew over phases — so
    LogReport/PrintReport track it like any metric; the full per-phase
    attribution lands in :attr:`last_report` and (rank 0, optional)
    ``<out>/straggler.jsonl``.

    Args:
      comm: communicator (``allgather_obj`` + rank identity).
      recorder: flight recorder to drain (default the global one).
      phases: restrict attribution to these span names (default: every
        span name recorded in the interval).
      write: append each report as a JSON line to
        ``<trainer.out>/straggler.jsonl`` on rank 0.
    """

    trigger = (1, "epoch")
    priority = 85   # before LogReport (50): the observation must exist
    # when the log entry for the same tick is assembled

    def __init__(self, comm, recorder: Optional[TraceRecorder] = None,
                 phases: Optional[Sequence[str]] = None,
                 write: bool = True):
        self.comm = comm
        self.recorder = recorder
        self.phases = None if phases is None else set(phases)
        self.write = write
        self.last_report: Optional[dict] = None

    def _recorder(self) -> TraceRecorder:
        return self.recorder if self.recorder is not None \
            else get_recorder()

    def __call__(self, trainer=None) -> None:
        rec = self._recorder()
        # a phase filter drains ONLY its names, so reports with
        # disjoint filters on different triggers never steal each
        # other's accumulated intervals
        local = rec.drain_phase_stats(
            None if self.phases is None else sorted(self.phases))
        rows = {name: {"mean": s["total_s"] / max(s["count"], 1),
                       "hist": s["hist"]}
                for name, s in local.items()}
        # collective: every process calls, even with an empty interval
        gathered = self.comm.allgather_obj(rows)
        phases: Dict[str, dict] = {}
        worst = 1.0
        for name in sorted(set().union(*(d.keys() for d in gathered))
                           if gathered else ()):
            # rows may be bare floats (older shards / hand-built test
            # fakes) or the {"mean", "hist"} dicts recorded here
            per_rank = {}
            hists = {}
            for r, d in enumerate(gathered):
                if name not in d:
                    continue
                val = d[name]
                if isinstance(val, dict):
                    per_rank[r] = val["mean"]
                    if val.get("hist") is not None:
                        hists[r] = val["hist"]
                else:
                    per_rank[r] = float(val)
            mean = sum(per_rank.values()) / len(per_rank)
            slowest_rank = max(per_rank, key=per_rank.get)
            skew = (per_rank[slowest_rank] / mean) if mean > 0 else 1.0
            phases[name] = {
                "mean_s": mean,
                "slowest_rank": slowest_rank,
                "slowest_s": per_rank[slowest_rank],
                "skew": skew,
                "ranks": len(per_rank),
            }
            if hists:
                # tail attribution on the shared lattice: the merged
                # cross-rank distribution's p50/p99 (bucket-wise sum —
                # exact while the combined samples fit the cap), plus
                # which rank owns the worst p99 and how far its tail
                # sits from the fleet's — stragglers live in tails,
                # not means
                merged = Histogram()
                for h in hists.values():
                    merged.merge(h)
                p50, p99 = merged.percentile(50), merged.percentile(99)
                rank_p99 = {r: Histogram.from_snapshot(h).percentile(99)
                            for r, h in hists.items()}
                slowest_p99 = max(rank_p99, key=rank_p99.get)
                phases[name].update({
                    "p50_s": p50,
                    "p99_s": p99,
                    "slowest_rank_p99": slowest_p99,
                    "skew_p99": (rank_p99[slowest_p99] / p99
                                 if p99 else 1.0),
                })
            worst = max(worst, skew)
        self.last_report = {
            "iteration": (trainer.updater.iteration
                          if trainer is not None else None),
            "phases": phases,
            "max_skew": worst,
        }
        if trainer is not None:
            trainer.observation["main/straggler_skew"] = worst
        rec.instant("straggler/report", cat="telemetry",
                    max_skew=round(worst, 4))
        if (self.write and trainer is not None
                and getattr(self.comm, "inter_rank", 0) == 0):
            try:
                path = os.path.join(getattr(trainer, "out", "."),
                                    "straggler.jsonl")
                # atomic per line (metrics.append_jsonl): a SIGKILL
                # mid-flush must never tear the series' last line
                append_jsonl(path, self.last_report)
            except OSError:
                pass


class MetricsExport:
    """JSONL time-series appender for ``trainer.observation``.

    Each trigger appends ONE line — iteration, epoch, elapsed wall
    clock, wall timestamp, and every float-coercible observation
    (optionally filtered by ``keys``) — to ``<trainer.out>/<filename>``.
    Each line lands via the atomic single-write append
    (:func:`chainermn_tpu.utils.metrics.append_jsonl`), so the series
    survives a crash — including a SIGKILL mid-write — with no torn
    last line.  The structured, machine-readable sibling of LogReport's
    interval-averaged ``log`` (which rewrites the whole file each
    fire): this one is append-only and per-tick, the format scrapers
    and dashboards want.
    """

    trigger = (1, "iteration")
    priority = 45   # after ObservationAggregator (90) and the straggler
    # report (85) so aggregated/derived values are in the dict

    def __init__(self, path: Optional[str] = None,
                 filename: str = "metrics.jsonl",
                 keys: Optional[Sequence[str]] = None):
        self.path = path
        self.filename = filename
        self.keys = None if keys is None else list(keys)
        self._dir_made = False

    def initialize(self, trainer) -> None:
        if self.path is None:
            self.path = os.path.join(
                getattr(trainer, "out", "."), self.filename)

    def __call__(self, trainer) -> None:
        if self.path is None:       # used without initialize()
            self.initialize(trainer)
        obs = trainer.observation
        keys = self.keys if self.keys is not None else list(obs)
        entry = {
            "iteration": trainer.updater.iteration,
            "epoch": trainer.updater.epoch,
            "elapsed_time": trainer.elapsed_time,
            "ts": time.time(),
        }
        for k in keys:
            if k not in obs:
                continue
            try:
                entry[k] = float(obs[k])
            except (TypeError, ValueError):
                continue
        try:
            if not self._dir_made:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                self._dir_made = True
            append_jsonl(self.path, entry)
        except OSError:
            pass                    # observability must never kill training

    def finalize(self, trainer=None) -> None:
        pass                        # nothing held open between lines
