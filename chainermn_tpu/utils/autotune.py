"""Measured collective autotuner: empirical exchange-plan search with a
persistent plan cache.

ChainerMN shipped a zoo of communicators (naive / flat / hierarchical /
two_dimensional / pure_nccl) and made the USER pick one per cluster;
this repo's exchange strategy has so far been picked analytically
(``choose_bucket_bytes``, ``fused_collective_budget``) from PUBLISHED
interconnect constants.  Both approaches guess.  The measured spread is
real money — PR 1 recorded a 1.75×/2.1× gap between strategies on the
same payload — and search-based collective systems (HiCCL,
arXiv:2408.05962; GC3, arXiv:2201.11840) close exactly this gap by
timing candidates on the real machine.  This module is that search,
sized to the repo's strategy space:

1. **enumerate** — {per-leaf, fused-flat, hierarchical 2-stage,
   reduce-scatter→all-gather} × a geometric bucket grid centred on the
   analytic ``b*`` × wire dtype {native, bf16}
   (:func:`enumerate_candidates`);
2. **prune** — rank candidates with the existing ``comm_model``
   latency–bandwidth cost model and keep the top-k
   (:func:`model_cost`), so probing stays a handful of compiles;
3. **measure** — compile each survivor on the LIVE mesh against the
   actual gradient pytree signature, warmup-discarded median of
   ``trials`` runs, every candidate parity-checked (allclose) against
   the per-leaf baseline before it may win (:func:`autotune_plan`);
4. **persist** — the winning :class:`Plan` lands in an on-disk JSON
   cache keyed by (mesh/topology signature, payload signature,
   backend + jax version), so later runs warm-start with ZERO probe
   executions (:func:`load_cached_plan` / :func:`store_plan`).

Probe timings also feed a least-squares
:class:`~chainermn_tpu.utils.comm_model.LinkParams` fit, so the plan
carries measured latency/bandwidth constants that recalibrate the
analytic models (``choose_bucket_bytes(link=...)``,
``choose_accum_steps(link=...)``) for every later decision.

Multi-process discipline: probing is SPMD (every process runs the same
candidate programs — a collective cannot run on one rank), but ONLY
rank 0's measured decision is authoritative: the winning plan dict is
broadcast over the communicator's object channel (the KV store in
multi-process runs) and every rank adopts it, so all ranks compile the
IDENTICAL exchange program even when timing noise would have ranked
candidates differently per host.

Drift guard: a :class:`PlanCell` carries the resolved plan plus the
latest observed exchange time (``StandardUpdater``'s
``main/exchange_time``); when the observation departs from the plan's
measured time by more than ``drift_factor`` in either direction the
cell flags ``drifted`` and :meth:`PlanCell.retune` re-runs the search
with ``force=True``.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from chainermn_tpu.utils.comm_model import (
    LinkParams,
    choose_bucket_bytes,
    fused_collective_budget,
)

__all__ = [
    "FORMAT_VERSION",
    "PLAN_CACHE_ENV",
    "Candidate",
    "Plan",
    "PlanCell",
    "autotune_pattern_plan",
    "autotune_plan",
    "build_exchange_fn",
    "build_pattern_probe_fn",
    "build_plan_probe",
    "default_cache_path",
    "enumerate_candidates",
    "load_cached_plan",
    "mesh_signature",
    "model_cost",
    "payload_signature",
    "plan_key",
    "store_plan",
]

# Bump to invalidate every cached plan (plan semantics / probe harness
# changes make old measurements incomparable).
# v2: plans gained the overlap *schedule* dimension (strategy
# "overlap" + per-bucket eager/deferred modes); v1 plans carry no
# schedule field and their measurements never saw the overlap
# candidates, so they must re-tune.
# v3: plans gained the ``program`` field (collective-plan IR programs
# for the pattern tuner below) — v2 caches are silently re-tuned, the
# documented migration path (see docs/TUNING.md "Plan IR")
FORMAT_VERSION = 3

PLAN_CACHE_ENV = "CHAINERMN_TPU_PLAN_CACHE"

# bf16 wire itemsize — what the compressed wire variant costs per element
_WIRE_ITEMSIZE = 2


@dataclass(frozen=True)
class Candidate:
    """One point of the exchange-plan search space."""

    strategy: str                       # one of ops.fused.PLAN_STRATEGIES
    bucket_bytes: int
    wire_dtype: Optional[str] = None    # "bfloat16" or None (native)
    # overlap schedule: ((n_leaves, mode, via), ...) over the REVERSED
    # non-empty-leaf order (see ops.fused.overlap_exchange); None for
    # the window-end strategies
    schedule: Optional[Tuple[Tuple[int, str, str], ...]] = None

    def label(self) -> str:
        w = self.wire_dtype or "native"
        base = f"{self.strategy}/b{self.bucket_bytes}/{w}"
        if self.schedule is None:
            return base
        n_def = sum(1 for _, m, _ in self.schedule if m == "deferred")
        return f"{base}/s{len(self.schedule)}d{n_def}"

    def schedule_dicts(self) -> Optional[list]:
        """The schedule in the JSON-stable dict form a :class:`Plan`
        persists."""
        if self.schedule is None:
            return None
        return [{"leaves": k, "mode": m, "via": v}
                for k, m, v in self.schedule]


@dataclass
class Plan:
    """A tuned exchange plan — the autotuner's output and the static
    argument :func:`chainermn_tpu.ops.fused.plan_allreduce` executes.

    ``measured_ms`` is the winner's warmup-discarded median probe time;
    ``link`` carries the probe-fitted
    :class:`~chainermn_tpu.utils.comm_model.LinkParams` as a plain dict
    (JSON-stable); ``meta`` records the full candidate report (mesh /
    payload signatures, per-candidate timings) for auditability.
    ``from_cache`` / ``n_probes`` describe how THIS process obtained
    the plan (volatile — never persisted): a cache warm-start reports
    ``from_cache=True, n_probes=0``.
    """

    strategy: str
    bucket_bytes: int
    wire_dtype: Optional[str] = None
    # overlap schedule — list of {"leaves", "mode", "via"} dicts over
    # the reversed non-empty-leaf order; None for window-end strategies
    # (strategy "overlap" with schedule=None derives the all-eager
    # default from bucket_bytes at trace time)
    schedule: Optional[list] = None
    # collective-plan IR program (``ops.plan_ir.PlanProgram.to_dict``
    # form) for pattern plans tuned by :func:`autotune_pattern_plan`;
    # None for the classic allreduce-strategy plans
    program: Optional[dict] = None
    measured_ms: Optional[float] = None
    key: Optional[str] = None
    link: Optional[Dict[str, float]] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    from_cache: bool = False
    n_probes: int = 0

    def to_dict(self) -> dict:
        return {
            "strategy": self.strategy,
            "bucket_bytes": int(self.bucket_bytes),
            "wire_dtype": self.wire_dtype,
            "schedule": self.schedule,
            "program": self.program,
            "measured_ms": self.measured_ms,
            "key": self.key,
            "link": self.link,
            "meta": self.meta,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        return cls(
            strategy=d["strategy"],
            bucket_bytes=int(d["bucket_bytes"]),
            wire_dtype=d.get("wire_dtype"),
            schedule=d.get("schedule"),
            program=d.get("program"),
            measured_ms=d.get("measured_ms"),
            key=d.get("key"),
            link=d.get("link"),
            meta=d.get("meta") or {},
        )

    @classmethod
    def from_any(cls, obj) -> "Plan":
        """Coerce a plan carrier (Plan, dict) to a :class:`Plan`."""
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls.from_dict(obj)
        raise TypeError(f"cannot build a Plan from {type(obj).__name__}")

    @property
    def link_params(self) -> Optional[LinkParams]:
        if not self.link:
            return None
        return LinkParams(
            latency_s=float(self.link["latency_s"]),
            bandwidth_bytes_per_s=float(
                self.link["bandwidth_bytes_per_s"]))


# --------------------------------------------------------------------- #
# signatures & cache keys
# --------------------------------------------------------------------- #


def _digest(obj) -> str:
    return hashlib.sha1(
        json.dumps(obj, sort_keys=True, default=str).encode()).hexdigest()


def mesh_signature(mesh, hier_shape: Optional[Tuple[int, int]] = None) \
        -> dict:
    """Topology signature a plan is valid for: device count and kinds,
    process count, the hierarchical (inter, intra) factoring if one
    exists, backend platform and jax version.  Any change — a different
    slice shape, a software upgrade — must miss the cache and re-tune:
    a plan measured on one topology says nothing about another."""
    import jax

    devs = list(np.asarray(mesh.devices).reshape(-1))
    return {
        "n_devices": len(devs),
        "device_kinds": sorted({str(d.device_kind) for d in devs}),
        "n_processes": int(jax.process_count()),
        "hier_shape": list(hier_shape) if hier_shape else None,
        "backend": str(jax.default_backend()),
        "jax_version": jax.__version__,
        "format_version": FORMAT_VERSION,
    }


def payload_signature(tree) -> dict:
    """Signature of the gradient pytree a plan is tuned against:
    per-dtype byte totals (wire compression applies per dtype group),
    leaf count, total bytes, and a digest of the exact
    (treedef, shapes, dtypes) so any architectural change re-tunes."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(tree)
    shapes = []
    groups: Dict[str, int] = {}
    n_nonempty = 0
    for leaf in leaves:
        dt = str(jnp.dtype(leaf.dtype))
        shape = tuple(int(s) for s in leaf.shape)
        shapes.append((shape, dt))
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = size * jnp.dtype(leaf.dtype).itemsize
        if size:
            n_nonempty += 1
            groups[dt] = groups.get(dt, 0) + nbytes
    return {
        "n_leaves": len(leaves),
        "n_nonempty": n_nonempty,
        "total_bytes": sum(groups.values()),
        "groups": groups,
        "digest": _digest([str(treedef), shapes]),
    }


def plan_key(mesh_sig: dict, payload_sig: dict,
             variant: Optional[str] = None) -> str:
    """Cache key: hash of the full mesh signature plus the payload
    digest.  Everything a measurement depends on is inside.

    ``variant`` separates searches run under different FAMILY
    constraints over the same (mesh, payload) — ``"overlap"`` (winner
    forced into the backward-overlapped family) and ``"overlap-auto"``
    (overlap candidates added to the open space) must not share cache
    entries with the window-end-only search: a hit from one would
    silently serve the other a plan its constraint forbids."""
    d = {"mesh": mesh_sig, "payload": payload_sig["digest"]}
    if variant:
        d["variant"] = variant
    return _digest(d)


# --------------------------------------------------------------------- #
# persistent plan cache
# --------------------------------------------------------------------- #


def default_cache_path() -> str:
    """``$CHAINERMN_TPU_PLAN_CACHE`` if set, else
    ``~/.cache/chainermn_tpu/plan_cache.json``."""
    env = os.environ.get(PLAN_CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "chainermn_tpu", "plan_cache.json")


def _load_cache_file(path: str) -> dict:
    try:
        with open(path) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return {"format": FORMAT_VERSION, "plans": {}}
    if cache.get("format") != FORMAT_VERSION:
        # incompatible cache format: treat as empty (re-tune), never crash
        return {"format": FORMAT_VERSION, "plans": {}}
    cache.setdefault("plans", {})
    return cache


def load_cached_plan(key: str, path: Optional[str] = None) \
        -> Optional[Plan]:
    """The cached plan for ``key``, or None (miss / unreadable file)."""
    path = path or default_cache_path()
    entry = _load_cache_file(path)["plans"].get(key)
    if entry is None:
        return None
    try:
        plan = Plan.from_dict(entry)
    except (KeyError, TypeError, ValueError):
        return None
    plan.from_cache = True
    plan.n_probes = 0
    return plan


def store_plan(plan: Plan, path: Optional[str] = None) -> str:
    """Persist ``plan`` under its key.  Returns the cache path.

    Merge-on-write under an advisory lock: the read-modify-replace runs
    with ``flock`` held on a sibling lockfile, so two jobs tuning
    DIFFERENT keys against the same cache file cannot drop each other's
    entries (the classic lost update — the loser would silently
    re-probe on its next launch).  The write itself stays atomic
    (tmp + rename), so readers never observe a torn file even where
    flock is advisory-only.
    """
    if not plan.key:
        raise ValueError("plan has no key; tune through autotune_plan")
    path = path or default_cache_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _merge_and_write():
        cache = _load_cache_file(path)
        cache["plans"][plan.key] = plan.to_dict()
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    try:
        import fcntl

        with open(path + ".lock", "a") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                _merge_and_write()
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    except ImportError:  # pragma: no cover - non-POSIX
        _merge_and_write()
    return path


# --------------------------------------------------------------------- #
# candidate space & cost model
# --------------------------------------------------------------------- #


def _wire_bytes_total(payload_sig: dict, wire_dtype: Optional[str]) -> int:
    """Total bytes crossing the wire for this payload under
    ``wire_dtype`` — per dtype group, floats compress to the wire
    itemsize, non-floats ride native (the packer's exemption)."""
    import jax.numpy as jnp

    total = 0
    for dt, nbytes in payload_sig["groups"].items():
        dtype = jnp.dtype(dt)
        if wire_dtype is not None and jnp.issubdtype(dtype, jnp.floating):
            total += (nbytes // dtype.itemsize) * min(_WIRE_ITEMSIZE,
                                                      dtype.itemsize)
        else:
            total += nbytes
    return total


def _compressible(payload_sig: dict) -> bool:
    """Whether a bf16 wire variant changes any bytes at all."""
    import jax.numpy as jnp

    return any(
        jnp.issubdtype(jnp.dtype(dt), jnp.floating)
        and jnp.dtype(dt).itemsize > _WIRE_ITEMSIZE
        for dt in payload_sig["groups"])


def _n_buckets(payload_sig: dict, cand: Candidate) -> int:
    """Bucket count the fused packer emits: per dtype group,
    ``ceil(group_wire_bytes / bucket)`` (matches flatten_buckets)."""
    import jax.numpy as jnp

    n = 0
    for dt, nbytes in payload_sig["groups"].items():
        dtype = jnp.dtype(dt)
        if cand.wire_dtype is not None \
                and jnp.issubdtype(dtype, jnp.floating):
            wire = (nbytes // dtype.itemsize) * min(_WIRE_ITEMSIZE,
                                                    dtype.itemsize)
        else:
            wire = nbytes
        if wire:
            n += fused_collective_budget(wire, cand.bucket_bytes)
    return max(n, 1)


def candidate_wire_stats(cand: Candidate, payload_sig: dict,
                         axis_size: int, inter_size: int = 1) \
        -> Tuple[int, float]:
    """``(collective_launches, ring_wire_bytes_per_device)`` for one
    candidate — the analytic inputs to :func:`model_cost` and the
    :class:`LinkParams` probe fit."""
    w = _wire_bytes_total(payload_sig, cand.wire_dtype)
    n = max(axis_size, 1)
    frac = (n - 1) / n if n > 1 else 0.0
    if cand.strategy == "per_leaf":
        return max(payload_sig["n_nonempty"], 1), 2.0 * w * frac
    if cand.strategy == "overlap":
        # ring bytes match the all-reduce; launches follow the
        # schedule's per-bucket collective choice (rs→ag = 2, ar = 1)
        if cand.schedule:
            launches = sum(2 if via == "rs" else 1
                           for _, _, via in cand.schedule)
        else:
            launches = 2 * _n_buckets(payload_sig, cand)
        return launches, 2.0 * w * frac
    buckets = _n_buckets(payload_sig, cand)
    if cand.strategy == "fused_flat":
        return buckets, 2.0 * w * frac
    if cand.strategy == "reduce_scatter":
        # rs + ag, each s(n-1)/n of the full tensor: allreduce bytes,
        # two launches per bucket
        return 2 * buckets, 2.0 * w * frac
    if cand.strategy == "hierarchical":
        # the world factors n = k (intra) × m (inter): the two intra
        # halves each move w(k-1)/k, and the inter all-reduce runs on
        # the 1/k-sized SHARD — 2(w/k)(m-1)/m (using 1/n there would
        # understate the inter stage by m× and flatter hierarchical
        # candidates in the pruning AND the LinkParams fit)
        m = max(inter_size, 1)
        intra_size = max(n // m, 1)
        frac_k = (intra_size - 1) / intra_size if intra_size > 1 else 0.0
        intra = 2.0 * w * frac_k
        inter = 2.0 * (w / intra_size) * ((m - 1) / m if m > 1 else 0.0)
        return 3 * buckets, intra + inter
    raise ValueError(f"unknown strategy {cand.strategy!r}")


def model_cost(cand: Candidate, payload_sig: dict, axis_size: int,
               inter_size: int = 1,
               link: Optional[LinkParams] = None) -> float:
    """Modeled seconds for one candidate:
    ``launches * latency + wire_bytes / bandwidth`` — the pruning
    metric (step 2).  Deliberately the SAME latency–bandwidth family
    as ``choose_bucket_bytes``; the measurement (step 3) is what
    corrects its errors."""
    link = link or LinkParams()
    launches, wire = candidate_wire_stats(cand, payload_sig, axis_size,
                                          inter_size)
    return launches * link.latency_s + wire / link.bandwidth_bytes_per_s


def _overlap_schedules(leaf_template, bucket_bytes: int,
                       wire_dtype: Optional[str]) -> List[Tuple]:
    """Schedule variants for one (bucket, wire) point: the all-eager
    reverse-layer stream in both per-bucket collective forms
    (``via="rs"`` reduce-scatter→all-gather, ``via="ar"`` one
    all-reduce — which form the backend schedules better is exactly
    what the probe settles), plus — when the stream has at least two
    buckets — a defer-tail variant holding the last quarter of the
    stream (the FIRST layers' gradients, produced when the backward is
    almost done and there is little compute left to hide under) back
    to the window end, where they contend with nothing."""
    from chainermn_tpu.ops.fused import build_overlap_schedule

    base = tuple(
        (e["leaves"], e["mode"], e["via"])
        for e in build_overlap_schedule(leaf_template, bucket_bytes,
                                        wire_dtype))
    out = []
    for via in ("rs", "ar"):
        eager = tuple((lv, m, via) for lv, m, _ in base)
        out.append(eager)
        k = len(eager)
        if k >= 2:
            n_def = max(1, k // 4)
            out.append(tuple(
                (lv, "deferred" if i >= k - n_def else m, v)
                for i, (lv, m, v) in enumerate(eager)))
    return out


def _schedule_wire_buckets(leaf_template, cand: Candidate) \
        -> Tuple[List[float], List[str], List[int]]:
    """Per-bucket wire bytes (stream order), modes, and launch counts
    (2 for ``via="rs"``, 1 for ``"ar"`` — the rs-vs-ar dimension must
    reach the cost model, or the enumeration's whole point is priced
    identically) for one overlap candidate, from the leaf template its
    schedule was built over — the
    :func:`~chainermn_tpu.utils.comm_model.overlap_exposed_time`
    inputs."""
    import jax

    from chainermn_tpu.ops.fused import _wire_dtype_for

    sizes = []
    for leaf in jax.tree.leaves(leaf_template):
        ne = int(np.prod(leaf.shape, dtype=np.int64)) if leaf.shape else 1
        if not ne:
            continue
        sizes.append(
            ne * _wire_dtype_for(leaf.dtype, cand.wire_dtype).itemsize)
    sizes.reverse()
    buckets, modes, launches = [], [], []
    pos = 0
    for k, mode, via in cand.schedule:
        buckets.append(float(sum(sizes[pos: pos + k])))
        modes.append(mode)
        launches.append(2 if via == "rs" else 1)
        pos += k
    return buckets, modes, launches


def enumerate_candidates(
    payload_sig: dict,
    axis_size: int,
    allow_hierarchical: bool = False,
    link: Optional[LinkParams] = None,
    grid: Sequence[float] = (0.25, 1.0, 4.0),
    overlap: Any = False,
    leaf_template=None,
) -> List[Candidate]:
    """The full candidate space (step 1): strategies × a geometric
    bucket grid centred on the analytic optimum ``b*`` × wire dtype.
    The bf16 wire variants are skipped when no payload group would
    actually compress; ``per_leaf`` is a single point (no bucket/wire
    knobs) and is always first — it doubles as the parity baseline.

    ``overlap`` adds the backward-overlapped family: per bucket size ×
    wire dtype, concrete schedules built over ``leaf_template`` (a
    pytree of abstract or real leaves mirroring the gradient tree —
    the schedule dimension needs per-leaf sizes the payload signature
    alone does not carry).  ``overlap=True`` additionally DROPS the
    window-end strategies (the caller wants the overlap family;
    per_leaf stays as the parity anchor), while ``"auto"`` keeps the
    space open and lets measurement decide across families."""
    link = link or LinkParams()
    total = max(int(payload_sig["total_bytes"]), 1)
    b_star = choose_bucket_bytes(total, axis_size, link=link,
                                 min_bucket=1024)
    buckets = sorted({max(1024, min(int(b_star * f), total))
                      for f in grid})
    wires: Tuple[Optional[str], ...] = (None,)
    if _compressible(payload_sig):
        wires = (None, "bfloat16")
    cands = [Candidate("per_leaf", total, None)]
    if overlap and leaf_template is None:
        raise ValueError(
            "overlap candidates need leaf_template (the schedule "
            "dimension is built from per-leaf sizes)")
    if not (overlap is True):
        strategies = ["fused_flat", "reduce_scatter"]
        if allow_hierarchical:
            strategies.append("hierarchical")
        for strat in strategies:
            for b in buckets:
                for w in wires:
                    cands.append(Candidate(strat, b, w))
    if overlap:
        for b in buckets:
            for w in wires:
                for sched in _overlap_schedules(leaf_template, b, w):
                    cands.append(Candidate("overlap", b, w,
                                           schedule=sched))
    return cands


# --------------------------------------------------------------------- #
# live probing
# --------------------------------------------------------------------- #


def build_exchange_fn(mesh, axis_name: str, plan_like,
                      inter_axis_name: Optional[str] = None):
    """One jitted ``shard_map`` executing a plan/candidate's exchange on
    a WORLD-STACKED pytree (leading axis = mesh member, sharded over
    every mesh axis) — the probe harness, and the program
    ``StandardUpdater``'s exchange-time observer re-times.

    ``mesh`` may be 1-D (flat strategies) or 2-D ``(inter, intra)``
    with ``inter_axis_name`` naming the first axis (hierarchical)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.ops import fused as _fused

    axes = (inter_axis_name, axis_name) if inter_axis_name else (axis_name,)
    spec = P(axes if len(axes) > 1 else axis_name)

    def body(g):
        local = jax.tree.map(lambda a: a[0], g)
        red = _fused.plan_allreduce(local, axis_name, plan_like,
                                    inter_axis_name=inter_axis_name)
        return jax.tree.map(lambda a: a[None], red)

    from chainermn_tpu.utils.programs import ledger_jit

    # every probe candidate's compile lands in the program ledger
    # under one label — an autotune sweep that compiles N candidates
    # is N attributed ledger entries, not silent wall time
    return ledger_jit(jax.shard_map(
        body, mesh=mesh, in_specs=spec, out_specs=spec),
        label="autotune/exchange")


def build_plan_probe(comm, plan, params, zeros: bool = True):
    """The probe pair ``(fn, make_data)`` re-timing ``plan``'s exchange
    on ``comm``'s topology against ``params``-shaped world-stacked
    data — what ``StandardUpdater``'s ``main/exchange_time`` observer
    runs.

    ``fn`` is pre-warmed (compiled and executed once), so the caller's
    first timed run measures execution, not compilation.
    ``make_data()`` builds a fresh mesh-sharded probe tree per call —
    returned as a factory (not a tree) so callers that probe only
    occasionally don't pin a full gradient-tree's worth of device
    memory between probes.  ``zeros`` trades probe realism for
    allocation cost; timing is data-independent for these programs."""
    import jax
    import jax.numpy as jnp

    plan = Plan.from_any(plan)
    devices = list(np.asarray(comm.mesh.devices).reshape(-1))
    n = len(devices)
    axis_name = comm.axis_name
    from jax.sharding import Mesh

    flat_mesh = Mesh(np.asarray(devices, dtype=object), (axis_name,))
    inter_ax = None
    pm = flat_mesh
    if plan.strategy == "hierarchical":
        pm, inter_ax = _resolve_hier(comm, axis_name, None, None)
        if pm is None:
            raise ValueError(
                "hierarchical plan on a topology with no (inter, intra) "
                "factoring — the plan's mesh signature does not match "
                "this communicator")
    axes = (inter_ax, axis_name) if inter_ax else (axis_name,)

    def make_data():
        if zeros:
            data = jax.tree.map(
                lambda p: jnp.zeros(
                    (n,) + tuple(int(s) for s in p.shape),
                    jnp.dtype(p.dtype)), params)
        else:
            data = _probe_tree(params, n, seed=0)
        return _place(data, pm, axes)

    fn = build_exchange_fn(pm, axis_name, plan,
                           inter_axis_name=inter_ax)
    jax.block_until_ready(fn(make_data()))    # compile + warm
    return fn, make_data


def _place(data, mesh, axes: Tuple[str, ...]):
    """Device-put a world-stacked probe tree SHARDED over the mesh
    (leading axis split across every mesh axis) — unsharded placement
    would pile ``n×`` the payload onto one device and make every timed
    run pay a reshard."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(axes if len(axes) > 1 else axes[0]))
    return jax.tree.map(
        lambda a: jax.device_put(jnp.asarray(a), sh), data)


def _probe_tree(tree, n: int, seed: int):
    """Deterministic world-stacked probe data shaped like ``tree``:
    floats get seeded gaussians (rank-varying — the reduction must do
    real work), ints/bools get rank-identical values (their mean is
    then exact, so parity checks stay strict)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)

    def one(leaf):
        shape = (n,) + tuple(int(s) for s in leaf.shape)
        dtype = jnp.dtype(leaf.dtype)
        if jnp.issubdtype(dtype, jnp.floating):
            return rng.randn(*shape).astype(dtype)
        if dtype == jnp.bool_:
            return np.ones(shape, bool)
        row = rng.randint(0, 1 << 16, size=shape[1:])
        return np.broadcast_to(row, shape).astype(dtype)

    return jax.tree.map(one, tree)


def _time_candidate(fn, data, trials: int, warmup: int) \
        -> Tuple[float, Any]:
    """Warmup-discarded median seconds over ``trials`` runs; returns
    ``(median_s, last_output)`` (the output feeds the parity check)."""
    import jax

    out = None
    for _ in range(max(warmup, 1)):       # first call compiles
        out = jax.block_until_ready(fn(data))
    times = []
    for _ in range(max(trials, 1)):
        t0 = time.perf_counter()
        out = fn(data)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return statistics.median(times), out


def _parity_ok(got, want, wire_dtype: Optional[str]) -> bool:
    import jax

    tol = 5e-2 if wire_dtype else 1e-5
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        g = np.asarray(g, dtype=np.float64)
        w = np.asarray(w, dtype=np.float64)
        if g.shape != w.shape:
            return False
        if g.size and not np.allclose(g, w, rtol=tol, atol=tol):
            return False
    return True


def _resolve_hier(comm, axis_name: str,
                  inter_axis_name: Optional[str], hier_mesh):
    """The 2-D (inter, intra) probing mesh, if the topology has one:
    an explicit ``hier_mesh`` wins; otherwise the communicator's
    host factoring (``_hier_factors``) builds it — the same layout
    ``TpuXlaCommunicator._fused_mean`` reduces over."""
    from jax.sharding import Mesh

    if hier_mesh is not None:
        if len(hier_mesh.axis_names) != 2:
            raise ValueError(
                f"hier_mesh must be 2-D (inter, intra); got axes "
                f"{hier_mesh.axis_names}")
        return hier_mesh, inter_axis_name or hier_mesh.axis_names[0]
    factors = getattr(comm, "_hier_factors", None)
    if not callable(factors):
        return None, None
    h = factors()
    if h is None:
        return None, None
    rows, _ = h
    inter = inter_axis_name or axis_name + "_inter"
    return Mesh(np.asarray(rows, dtype=object), (inter, axis_name)), inter


def autotune_plan(
    comm,
    params,
    *,
    axis_name: Optional[str] = None,
    mesh=None,
    hier_mesh=None,
    inter_axis_name: Optional[str] = None,
    allow_hierarchical: Optional[bool] = None,
    cache_path: Optional[str] = None,
    top_k: int = 5,
    trials: int = 3,
    warmup: int = 1,
    grid: Sequence[float] = (0.25, 1.0, 4.0),
    overlap: Any = False,
    t_bwd_s: Optional[float] = None,
    overlap_slack: float = 0.15,
    force: bool = False,
    seed: int = 0,
) -> Plan:
    """Tune (or warm-start) the exchange plan for ``params``-shaped
    gradients on the live mesh.

    Args:
      comm: communicator supplying the mesh, axis, topology factoring
        and the rank-0 plan broadcast (``bcast_obj``).  May be ``None``
        when ``mesh`` + ``axis_name`` are given (bench/test harnesses).
      params: pytree whose leaves' (shape, dtype) signature matches the
        gradients the plan will exchange (grads mirror params
        leaf-for-leaf).  Values are never read — probe data is
        generated — so abstract leaves (``ShapeDtypeStruct``) work too.
      axis_name / mesh: override the communicator's (required without
        one).  ``mesh`` must be flat (1-D) — it is re-flattened over
        its devices regardless.
      hier_mesh / inter_axis_name: explicit 2-D ``(inter, intra)`` mesh
        enabling hierarchical candidates (default: derived from the
        communicator's host factoring; single-host worlds have none).
      allow_hierarchical: force-include/exclude hierarchical candidates
        (default: included exactly when a 2-D mesh is available).
      cache_path: plan-cache file (default
        :func:`default_cache_path`; env ``CHAINERMN_TPU_PLAN_CACHE``).
      top_k: candidates surviving the model-cost pruning (the per-leaf
        baseline is always probed on top — it anchors parity).
      trials / warmup: probe repetitions; the warmup runs (compile +
        first execution) are discarded, the median of ``trials`` wins.
      grid: geometric bucket-size factors around the analytic ``b*``.
      overlap: search the backward-overlapped exchange family
        (strategy ``"overlap"`` — the plan gains a *schedule*: bucket
        boundaries over the reversed leaf order plus per-bucket
        eager/deferred modes).  ``True`` forces the winner into that
        family (per-leaf stays as the parity anchor only); ``"auto"``
        adds overlap candidates to the open space and lets the
        measurement decide; ``False`` (default) keeps the window-end
        space.  The constraint is part of the cache key (``variant``),
        so overlap and window-end tunings never serve each other.
      t_bwd_s: measured backward wall time per microbatch (e.g. the
        updater's ``main/step_time`` before the exchange dominates) —
        the overlap schedule ranking's hiding budget.  An isolated
        probe times TOTAL wire cost but cannot see what overlap hides,
        so with ``t_bwd_s`` given the overlap winner minimises the
        modeled EXPOSED time
        (:func:`~chainermn_tpu.utils.comm_model.overlap_exposed_time`
        fed each candidate's probe-calibrated per-bucket wire times);
        without it, the ``overlap_slack`` rule applies.
      overlap_slack: with no ``t_bwd_s``, the overlap winner is the
        candidate with the MOST eager stream buckets among those
        within ``(1 + overlap_slack)×`` of the fastest overlap
        candidate's isolated time — finer buckets buy overlap room at
        bounded wire cost, and a single-bucket "schedule" (which a
        pure isolated-time ranking favours: fewest launches) would
        re-create the window-end join the family exists to remove.
      force: ignore (and overwrite) any cached plan — the drift
        guard's re-tune entry point.
      seed: probe-data seed (deterministic across ranks: probe inputs
        must be SPMD-identical).

    Returns the winning :class:`Plan`; ``plan.from_cache`` /
    ``plan.n_probes`` report whether any probe actually executed.
    """
    import jax
    from jax.sharding import Mesh

    if comm is not None:
        axis_name = axis_name or comm.axis_name
        mesh = mesh if mesh is not None else comm.mesh
    if mesh is None or axis_name is None:
        raise ValueError("autotune_plan needs comm, or mesh + axis_name")

    leaves = jax.tree.leaves(params)
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        raise RuntimeError(
            "autotune_plan called under tracing — the autotuner runs "
            "REAL probe programs and cannot execute inside jit/shard_"
            "map.  Resolve the plan eagerly first (e.g. call the "
            "multi-node optimizer's init(params) outside jit, the "
            "StandardUpdater contract).")

    devices = list(np.asarray(mesh.devices).reshape(-1))
    n = len(devices)
    flat_mesh = Mesh(np.asarray(devices, dtype=object), (axis_name,))
    hmesh, inter_ax = _resolve_hier(comm, axis_name, inter_axis_name,
                                    hier_mesh)
    if allow_hierarchical is None:
        allow_hierarchical = hmesh is not None
    if allow_hierarchical and hmesh is None:
        raise ValueError(
            "allow_hierarchical=True but no 2-D (inter, intra) mesh is "
            "available: pass hier_mesh or use a multi-host communicator")
    hier_shape = (tuple(int(s) for s in np.asarray(hmesh.devices).shape)
                  if (hmesh is not None and allow_hierarchical) else None)
    inter_size = hier_shape[0] if hier_shape else 1

    payload = payload_signature(params)
    mesh_sig = mesh_signature(flat_mesh, hier_shape)
    variant = None
    if overlap:
        variant = "overlap" if overlap is True else "overlap-auto"
    key = plan_key(mesh_sig, payload, variant=variant)

    if not force:
        cached = local_hit = load_cached_plan(key, cache_path)
        if comm is not None:
            # The hit/miss decision must be SPMD-agreed: probing and
            # the winner broadcast below are COLLECTIVE, so per-host
            # cache files that disagree (rank 0 warm, rank 1 cold)
            # would strand the cold ranks in collectives the warm ones
            # never enter.  Rank 0's verdict is authoritative — a
            # rank-0 hit serves everyone, a rank-0 miss re-tunes
            # everywhere.
            served = comm.bcast_obj(
                cached.to_dict() if cached is not None else None,
                root=0)
            cached = (Plan.from_dict(served) if served is not None
                      else None)
            if cached is not None:
                cached.from_cache = True
                cached.n_probes = 0
                if local_hit is None:
                    try:
                        # warm this rank's cold local file, so a later
                        # run of it hits without the broadcast
                        store_plan(cached, cache_path)
                    except OSError:
                        pass
        from chainermn_tpu.utils.metrics import get_registry

        if cached is not None:
            get_registry().inc("autotune/plan_cache_hits")
            return cached
        # counted only when the lookup actually ran and came up empty:
        # a force=True retune (the drift guard's path) never consults
        # the cache, so it must not depress the scraped hit rate
        get_registry().inc("autotune/plan_cache_misses")

    # -- enumerate + prune -------------------------------------------- #
    leaf_template = None
    if overlap:
        leaf_template = [jax.ShapeDtypeStruct(tuple(int(s)
                                                    for s in l.shape),
                                              l.dtype)
                         for l in leaves]
    cands = enumerate_candidates(payload, n,
                                 allow_hierarchical=allow_hierarchical,
                                 grid=grid, overlap=overlap,
                                 leaf_template=leaf_template)
    baseline, rest = cands[0], cands[1:]

    def _prune_cost(c: Candidate) -> float:
        base = model_cost(c, payload, n, inter_size)
        if t_bwd_s is not None and c.strategy == "overlap" \
                and c.schedule:
            # prune with the objective the final ranking uses: a fine
            # schedule's extra launches make its ISOLATED cost high,
            # but most of them hide under the backward — ranking the
            # prune by isolated cost would drop exactly the schedules
            # the exposed-time model exists to find
            from chainermn_tpu.utils.comm_model import (
                overlap_exposed_time,
            )

            bkts, modes, launches = _schedule_wire_buckets(
                leaf_template, c)
            return overlap_exposed_time(
                bkts, n, float(t_bwd_s), modes=modes,
                launches_per_bucket=launches) + 1e-6 * base
        return base

    k = max(top_k, 1)
    if overlap and overlap is not True:
        # open ("auto") space: prune PER FAMILY.  With t_bwd_s given,
        # overlap candidates' exposed-time cost is near zero while
        # window-end candidates carry their full isolated cost — a
        # single sorted list would fill every probe slot with overlap
        # schedules and the cross-family measurement "auto" promises
        # would never happen.
        ov_c = sorted((c for c in rest if c.strategy == "overlap"),
                      key=_prune_cost)
        we_c = sorted((c for c in rest if c.strategy != "overlap"),
                      key=_prune_cost)
        probed = [baseline] + ov_c[:(k + 1) // 2] + we_c[:k // 2]
    else:
        rest.sort(key=_prune_cost)
        probed = [baseline] + rest[:k]

    # -- measure ------------------------------------------------------ #
    n_probes = 0
    timings: List[dict] = []
    results: List[Tuple[Candidate, float]] = []
    ref_out = None
    raw = _probe_tree(params, n, seed)
    flat_data = _place(raw, flat_mesh, (axis_name,))
    hier_data = None
    from chainermn_tpu.utils.metrics import get_registry
    from chainermn_tpu.utils.telemetry import get_recorder

    tracer = get_recorder()
    for cand in probed:
        use_hier = cand.strategy == "hierarchical"
        if use_hier and hier_data is None:
            hier_data = _place(raw, hmesh, (inter_ax, axis_name))
        data = hier_data if use_hier else flat_data
        fn = build_exchange_fn(hmesh if use_hier else flat_mesh,
                               axis_name, cand.__dict__,
                               inter_axis_name=inter_ax if use_hier
                               else None)
        # span covers compile + warmup + trials; the elected median
        # rides the metadata, so the trace shows both what tuning COST
        # and what each candidate MEASURED
        with tracer.span("autotune/probe", cat="autotune",
                         strategy=cand.strategy,
                         bucket_bytes=cand.bucket_bytes,
                         wire_dtype=cand.wire_dtype) as probe_sp:
            median_s, out = _time_candidate(fn, data, trials, warmup)
            probe_sp.set(median_ms=round(median_s * 1e3, 4))
        n_probes += max(trials, 1) + max(warmup, 1)
        get_registry().inc("autotune/probes")
        get_registry().observe("autotune/probe_time", median_s)
        if cand.strategy == "per_leaf":
            ref_out = out
            ok = True
        else:
            ok = _parity_ok(out, ref_out, cand.wire_dtype)
        timings.append({
            "strategy": cand.strategy,
            "bucket_bytes": cand.bucket_bytes,
            "wire_dtype": cand.wire_dtype,
            "schedule": cand.schedule_dicts(),
            "ms": round(median_s * 1e3, 4),
            "modeled_ms": round(
                model_cost(cand, payload, n, inter_size) * 1e3, 4),
            "parity_ok": bool(ok),
        })
        if ok:
            results.append((cand, median_s))

    pool = results
    if overlap is True:
        # the caller asked for the backward-overlapped family: the
        # per-leaf baseline (and any parity survivor outside the
        # family) anchors correctness but may not win.  Fall back to
        # the open pool only if every overlap candidate failed parity.
        forced = [r for r in results if r[0].strategy == "overlap"]
        pool = forced or results
    winner, best_s = min(pool, key=lambda r: r[1])

    # Schedule-aware overlap ranking.  An isolated probe measures a
    # schedule's TOTAL wire cost but, with no backward running under
    # it, none of what overlap hides — so raw probe time favours the
    # single-bucket schedule (fewest launches), which is the window-end
    # join wearing the overlap strategy's name.
    ov = [r for r in pool if r[0].strategy == "overlap"
          and r[0].schedule]
    if ov and t_bwd_s is not None:
        # measured hiding budget: rank by modeled EXPOSED time, each
        # candidate's per-bucket wire times calibrated so their sum
        # equals its measured isolated probe time
        from chainermn_tpu.utils.comm_model import overlap_exposed_time

        frac = 2.0 * (n - 1) / n if n > 1 else 0.0
        lp0 = LinkParams()

        def _effective(r):
            cand, meas = r
            if cand.strategy != "overlap" or not cand.schedule:
                # a window-end exchange hides nothing: fully exposed
                return (meas, meas)
            bkts, modes, launches = _schedule_wire_buckets(
                leaf_template, cand)
            model_total = sum(
                k * lp0.latency_s + b * frac / lp0.bandwidth_bytes_per_s
                for b, k in zip(bkts, launches)) or float(meas)
            scale = meas / model_total
            exposed = overlap_exposed_time(
                bkts, n, float(t_bwd_s),
                latency_s=lp0.latency_s * scale,
                bandwidth_bytes_per_s=lp0.bandwidth_bytes_per_s / scale,
                modes=modes, launches_per_bucket=launches)
            return (exposed, meas)

        winner, best_s = min(pool, key=_effective)
    elif ov and (overlap is True or winner.strategy == "overlap"):
        # no hiding budget given: among overlap candidates within
        # overlap_slack of the fastest, take the FINEST eager stream —
        # more buckets at bounded wire cost is more overlap room.
        # Single-bucket schedules are excluded whenever a multi-bucket
        # candidate survived parity: one bucket cannot stream under
        # anything (it IS the window-end join), and on small payloads
        # its fewest-launches probe time would otherwise always win —
        # defeating the overlap request the caller made.
        multi = [r for r in ov if len(r[0].schedule) >= 2]
        pool_ov = multi or ov
        best_ov = min(s for _, s in pool_ov)
        eligible = [r for r in pool_ov
                    if r[1] <= best_ov * (1.0 + overlap_slack)]
        winner, best_s = min(
            eligible,
            key=lambda r: (-sum(1 for _, m, _ in r[0].schedule
                                if m == "eager"), r[1]))

    # -- fit measured link constants ---------------------------------- #
    samples = []
    for cand, t in results:
        launches, wire = candidate_wire_stats(cand, payload, n,
                                              inter_size)
        samples.append((launches, wire, t))
    link = LinkParams.from_probes(samples)

    plan = Plan(
        strategy=winner.strategy,
        bucket_bytes=winner.bucket_bytes,
        wire_dtype=winner.wire_dtype,
        schedule=winner.schedule_dicts(),
        measured_ms=round(best_s * 1e3, 4),
        key=key,
        link={"latency_s": link.latency_s,
              "bandwidth_bytes_per_s": link.bandwidth_bytes_per_s},
        meta={
            "mesh": mesh_sig,
            "payload": {k: v for k, v in payload.items()
                        if k != "groups"},
            "timings": timings,
            "n_enumerated": len(cands),
            "n_probed": len(probed),
            "overlap": overlap if overlap else False,
            "trials": trials,
            "created": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
        },
    )

    # -- rank-0 decision broadcast ------------------------------------ #
    # Probing was SPMD (all processes ran the same programs), but
    # timing noise is per-host: rank 0's winner is authoritative so
    # every rank compiles the identical exchange program.
    if comm is not None:
        plan = Plan.from_dict(comm.bcast_obj(plan.to_dict(), root=0))
    plan.n_probes = n_probes
    plan.from_cache = False

    # -- persist on EVERY process: cache paths default to host-local
    # files (each host must warm its own), and the flock'd
    # merge-on-write in store_plan makes a shared path multi-writer
    # safe (same key -> identical content, idempotent) --------------- #
    try:
        store_plan(plan, cache_path)
    except OSError:
        pass    # read-only FS: the plan still serves this run
    return plan


# --------------------------------------------------------------------- #
# pattern plans — the collective-plan IR search (ops.plan_ir)
# --------------------------------------------------------------------- #


def _program_uses_inter(program) -> bool:
    return any(st.axis == "inter" for st in program.steps)


def _program_enriched_steps(program, payload_sig: dict) -> List[dict]:
    """Plan-IR steps enriched with the launch counts and wire-dtype
    byte scaling :func:`~chainermn_tpu.utils.comm_model.program_cost`
    consumes — derived from the payload signature the same way the
    interpreter's fuse/cast_wire steps transform the lanes."""
    total = max(payload_sig["total_bytes"], 1)
    lanes = max(payload_sig["n_nonempty"], 1)
    from chainermn_tpu.utils.comm_model import PRIMITIVE_WIRE_KINDS

    wire_scale = 1.0
    fused = False
    out = []
    for st in program.steps:
        if st.op == "cast_wire":
            wire_scale = _wire_bytes_total(
                payload_sig, st.get("dtype")) / total
        elif st.op == "fuse":
            fused = True
        if st.op in PRIMITIVE_WIRE_KINDS:
            launches = (max(len(payload_sig["groups"]), 1)
                        if fused else lanes)
            launches *= int(st.get("chunks", 1))
            out.append({"op": st.op, "axis": st.axis or "main",
                        "launches": launches,
                        "bytes_scale": wire_scale})
    return out


def _pattern_axis_sizes(program, n: int, inter_size: int) \
        -> Dict[str, int]:
    if _program_uses_inter(program):
        return {"main": max(n // max(inter_size, 1), 1),
                "inter": max(inter_size, 1)}
    return {"main": n, "inter": 1}


def _pattern_model_cost(program, payload_sig: dict, n: int,
                        inter_size: int, link=None) -> float:
    from chainermn_tpu.utils.comm_model import program_cost

    return program_cost(
        _program_enriched_steps(program, payload_sig),
        payload_sig["total_bytes"],
        _pattern_axis_sizes(program, n, inter_size), link=link)


def _program_wire_stats(program, payload_sig: dict, n: int,
                        inter_size: int) -> Tuple[int, float]:
    """(total launches, total wire bytes/device) — the link-fit
    sample a probed program contributes."""
    from chainermn_tpu.utils.comm_model import (
        PRIMITIVE_WIRE_KINDS,
        wire_bytes_per_device,
    )

    sizes = _pattern_axis_sizes(program, n, inter_size)
    launches, wire = 0, 0.0
    for st in _program_enriched_steps(program, payload_sig):
        launches += st["launches"]
        wire += wire_bytes_per_device(
            PRIMITIVE_WIRE_KINDS[st["op"]],
            payload_sig["total_bytes"] * st["bytes_scale"],
            sizes[st["axis"]])
    return launches, wire


def _exact_ok(got, want) -> bool:
    """Bitwise parity — native plan-IR candidates are pure data
    movement, so anything short of exact equality is a lowering bug,
    not noise."""
    import jax

    gl, wl = jax.tree.leaves(got), jax.tree.leaves(want)
    if len(gl) != len(wl):
        return False
    for g, w in zip(gl, wl):
        g, w = np.asarray(g), np.asarray(w)
        if g.shape != w.shape or g.dtype != w.dtype \
                or not np.array_equal(g, w):
            return False
    return True


def build_pattern_probe_fn(mesh, axis_name: str, pattern: str, program,
                           inter_axis_name: Optional[str] = None,
                           **pattern_kw):
    """One jitted ``shard_map`` lowering ``program`` for ``pattern`` on
    a WORLD-STACKED payload (leading axis = mesh member) — the pattern
    tuner's probe harness, ledger-labelled ``plan_ir/<pattern>`` so
    every probe compile is attributed."""
    import jax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.ops import plan_ir

    program = plan_ir.ensure_program(program, pattern)
    axes = (inter_axis_name, axis_name) if inter_axis_name \
        else (axis_name,)
    spec = P(axes if len(axes) > 1 else axis_name)

    if pattern == "fsdp_gather":
        dims = pattern_kw["dims"]

        def lower(local):
            return plan_ir.lower_fsdp_gather(
                program, local, dims, axis_name=axis_name,
                inter_axis_name=inter_axis_name)
    elif pattern == "moe_all_to_all":
        sa = int(pattern_kw.get("split_axis", 0))
        ca = int(pattern_kw.get("concat_axis", 1))

        def lower(local):
            return plan_ir.lower_moe_all_to_all(
                program, local, axis_name=axis_name,
                split_axis=sa, concat_axis=ca)
    elif pattern == "ring_permute":
        def lower(local):
            leaves, treedef = jax.tree.flatten(local)
            return treedef.unflatten(list(plan_ir.lower_ring_permute(
                program, leaves, axis_name=axis_name)))
    elif pattern == "pipeline_edge":
        shift = int(pattern_kw.get("shift", 1))
        wrap = bool(pattern_kw.get("wrap", False))

        def lower(local):
            return plan_ir.lower_pipeline_edge(
                program, local, axis_name=axis_name, shift=shift,
                wrap=wrap)
    else:
        raise ValueError(f"unknown pattern {pattern!r}")

    def body(g):
        local = jax.tree.map(lambda a: a[0], g)
        out = lower(local)
        return jax.tree.map(lambda a: a[None], out)

    from chainermn_tpu.utils.programs import ledger_jit

    return ledger_jit(jax.shard_map(
        body, mesh=mesh, in_specs=spec, out_specs=spec),
        label=f"plan_ir/{pattern}")


def autotune_pattern_plan(
    comm,
    params,
    *,
    pattern: str,
    axis_name: Optional[str] = None,
    mesh=None,
    hier_mesh=None,
    inter_axis_name: Optional[str] = None,
    allow_hierarchical: Optional[bool] = None,
    wire_dtypes: Sequence = (None,),
    cache_path: Optional[str] = None,
    top_k: int = 6,
    trials: int = 3,
    warmup: int = 1,
    max_chunks: int = 8,
    force: bool = False,
    seed: int = 0,
    variant_extra: Optional[Dict[str, Any]] = None,
    **pattern_kw,
) -> Plan:
    """Tune (or warm-start) a collective-plan IR program for one
    communication ``pattern`` — the :func:`autotune_plan` search
    applied to the ``ops.plan_ir`` candidate spaces, riding the SAME
    plan-cache / rank-0-broadcast / drift-guard machinery.

    Args:
      comm / axis_name / mesh / hier_mesh / inter_axis_name /
        cache_path / trials / warmup / force / seed: exactly as
        :func:`autotune_plan`.
      pattern: one of ``ops.plan_ir.PATTERNS`` (``"fsdp_gather"``,
        ``"moe_all_to_all"``, ``"ring_permute"``, ``"pipeline_edge"``).
      params: the pattern's LOCAL payload template (per-device shard
        shapes): the sharded param subtree for ``fsdp_gather``, the
        ``(E, C, D)`` slots array for ``moe_all_to_all``, the
        ``(k, v)`` block pair for ``ring_permute``, the activation
        micro-batch for ``pipeline_edge``.  Values are never read.
      allow_hierarchical: include two-stage (intra→inter) candidates
        (``fsdp_gather`` only; default: exactly when a 2-D mesh is
        available).
      wire_dtypes: wire-compression dtypes to enumerate (``None`` =
        native; the non-float exemption applies per leaf).  Native
        candidates must match the baseline BITWISE; wire candidates
        get the usual tolerance.
      top_k: candidates surviving the per-primitive cost-model pruning
        (:func:`~chainermn_tpu.utils.comm_model.program_cost`).
      max_chunks: largest axis-split chunk count enumerated for
        ``moe_all_to_all``.
      variant_extra: extra JSON-stable key/value pairs folded into the
        cache key (NOT forwarded to lowering/probing) — consumers with
        their own payload discipline (``parallel.sharded_state``'s
        per-layer gather stream) namespace their plans so a tuning
        never serves a call site with different runtime structure.
      pattern_kw: pattern statics, part of the cache key — ``dims``
        (``fsdp_gather``), ``split_axis``/``concat_axis``
        (``moe_all_to_all``), ``shift``/``wrap`` (``pipeline_edge``).

    Returns the winning :class:`Plan` with ``plan.program`` holding
    the IR program dict (feed it to the pattern's ``plan=`` kwarg /
    ``ops.plan_ir.lower_*``); ``from_cache`` / ``n_probes`` report
    whether any probe executed.
    """
    import jax
    from jax.sharding import Mesh

    from chainermn_tpu.ops import plan_ir

    if pattern not in plan_ir.PATTERNS:
        raise ValueError(
            f"unknown pattern {pattern!r}; expected one of "
            f"{plan_ir.PATTERNS}")
    if comm is not None:
        axis_name = axis_name or comm.axis_name
        mesh = mesh if mesh is not None else comm.mesh
    if mesh is None or axis_name is None:
        raise ValueError(
            "autotune_pattern_plan needs comm, or mesh + axis_name")

    leaves = jax.tree.leaves(params)
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        raise RuntimeError(
            "autotune_pattern_plan called under tracing — the "
            "autotuner runs REAL probe programs and cannot execute "
            "inside jit/shard_map.  Resolve the plan eagerly first "
            "and pass it in via the call site's plan= kwarg.")

    devices = list(np.asarray(mesh.devices).reshape(-1))
    n = len(devices)
    flat_mesh = Mesh(np.asarray(devices, dtype=object), (axis_name,))
    hmesh, inter_ax = _resolve_hier(comm, axis_name, inter_axis_name,
                                    hier_mesh)
    if allow_hierarchical is None:
        allow_hierarchical = hmesh is not None \
            and pattern == "fsdp_gather"
    if allow_hierarchical and hmesh is None:
        raise ValueError(
            "allow_hierarchical=True but no 2-D (inter, intra) mesh is "
            "available: pass hier_mesh or use a multi-host communicator")
    hier_shape = (tuple(int(s) for s in np.asarray(hmesh.devices).shape)
                  if (hmesh is not None and allow_hierarchical) else None)
    inter_size = hier_shape[0] if hier_shape else 1

    payload = payload_signature(params)
    mesh_sig = mesh_signature(flat_mesh, hier_shape)
    # pattern statics fold into the variant: two tunings of the same
    # payload bytes under different dims / split axes / directions are
    # different searches and must never serve each other
    extras: Dict[str, Any] = {
        "pattern": pattern,
        "wire_dtypes": [None if w is None else str(np.dtype(w) if not
                        isinstance(w, str) else w)
                        for w in wire_dtypes],
    }
    for k, v in sorted(pattern_kw.items()):
        if k == "dims":
            treedef = jax.tree.structure(params)
            extras["dims"] = treedef.flatten_up_to(v)
        else:
            extras[k] = v
    if variant_extra:
        extras["variant_extra"] = {
            str(k): variant_extra[k] for k in sorted(variant_extra)}
    variant = f"plan-ir/{pattern}/{_digest(extras)[:12]}"
    key = plan_key(mesh_sig, payload, variant=variant)

    from chainermn_tpu.utils.metrics import get_registry
    from chainermn_tpu.utils.telemetry import get_recorder

    reg = get_registry()
    if not force:
        cached = local_hit = load_cached_plan(key, cache_path)
        if comm is not None:
            # SPMD-agreed hit/miss — same discipline as autotune_plan:
            # rank 0's verdict is authoritative so every process
            # enters (or skips) the collective probing together
            served = comm.bcast_obj(
                cached.to_dict() if cached is not None else None,
                root=0)
            cached = (Plan.from_dict(served) if served is not None
                      else None)
            if cached is not None:
                cached.from_cache = True
                cached.n_probes = 0
                if local_hit is None:
                    try:
                        store_plan(cached, cache_path)
                    except OSError:
                        pass
        if cached is not None:
            reg.inc("autotune/plan_cache_hits")
            reg.inc(f"autotune/plan_cache_hits_{pattern}")
            return cached
        reg.inc("autotune/plan_cache_misses")
        reg.inc(f"autotune/plan_cache_misses_{pattern}")

    # -- enumerate + prune (per-primitive cost terms) ----------------- #
    enum_kw: Dict[str, Any] = {"wire_dtypes": tuple(wire_dtypes)}
    if pattern == "fsdp_gather":
        enum_kw["allow_hierarchical"] = bool(allow_hierarchical)
    elif pattern == "moe_all_to_all":
        if len(leaves) != 1:
            raise ValueError(
                "moe_all_to_all payload must be the single slots "
                f"array; got {len(leaves)} leaves")
        enum_kw.update(
            shape=tuple(int(s) for s in leaves[0].shape),
            split_axis=int(pattern_kw.get("split_axis", 0)),
            concat_axis=int(pattern_kw.get("concat_axis", 1)),
            max_chunks=max_chunks)
    progs = plan_ir.enumerate_pattern_programs(pattern, **enum_kw)
    baseline, rest = progs[0], progs[1:]
    rest.sort(key=lambda p: _pattern_model_cost(p, payload, n,
                                                inter_size))
    probed = [baseline] + rest[:max(top_k, 1)]

    # -- measure ------------------------------------------------------ #
    n_probes = 0
    timings: List[dict] = []
    results: List[Tuple[Any, float]] = []
    ref_out = None
    raw = _probe_tree(params, n, seed)
    flat_data = _place(raw, flat_mesh, (axis_name,))
    hier_data = None
    tracer = get_recorder()
    for prog in probed:
        use_hier = _program_uses_inter(prog)
        if use_hier and hier_data is None:
            hier_data = _place(raw, hmesh, (inter_ax, axis_name))
        data = hier_data if use_hier else flat_data
        fn = build_pattern_probe_fn(
            hmesh if use_hier else flat_mesh, axis_name, pattern, prog,
            inter_axis_name=inter_ax if use_hier else None,
            **pattern_kw)
        with tracer.span("autotune/probe", cat="autotune",
                         pattern=pattern, label=prog.label,
                         wire_dtype=prog.wire_dtype) as probe_sp:
            median_s, out = _time_candidate(fn, data, trials, warmup)
            probe_sp.set(median_ms=round(median_s * 1e3, 4))
        n_probes += max(trials, 1) + max(warmup, 1)
        reg.inc("autotune/probes")
        reg.observe("autotune/probe_time", median_s)
        if prog is baseline:
            ref_out = out
            ok = True
        elif prog.wire_dtype:
            ok = _parity_ok(out, ref_out, prog.wire_dtype)
        else:
            # native candidates are pure data movement: bitwise or bust
            ok = _exact_ok(out, ref_out)
        timings.append({
            "label": prog.label,
            "wire_dtype": prog.wire_dtype,
            "ms": round(median_s * 1e3, 4),
            "modeled_ms": round(_pattern_model_cost(
                prog, payload, n, inter_size) * 1e3, 4),
            "parity_ok": bool(ok),
        })
        if ok:
            results.append((prog, median_s))
    winner, best_s = min(results, key=lambda r: r[1])

    # -- fit measured link constants ---------------------------------- #
    samples = []
    for prog, t in results:
        launches, wire = _program_wire_stats(prog, payload, n,
                                             inter_size)
        samples.append((launches, wire, t))
    link = LinkParams.from_probes(samples)

    plan = Plan(
        strategy=winner.label,
        bucket_bytes=0,
        wire_dtype=winner.wire_dtype,
        schedule=None,
        program=winner.to_dict(),
        measured_ms=round(best_s * 1e3, 4),
        key=key,
        link={"latency_s": link.latency_s,
              "bandwidth_bytes_per_s": link.bandwidth_bytes_per_s},
        meta={
            "pattern": pattern,
            "mesh": mesh_sig,
            "payload": {k: v for k, v in payload.items()
                        if k != "groups"},
            "extras": {k: v for k, v in extras.items()
                       if k != "pattern"},
            "timings": timings,
            "n_enumerated": len(progs),
            "n_probed": len(probed),
            "trials": trials,
            "created": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
        },
    )

    # rank-0 decision broadcast + persist on every process — same
    # rationale as autotune_plan
    if comm is not None:
        plan = Plan.from_dict(comm.bcast_obj(plan.to_dict(), root=0))
    plan.n_probes = n_probes
    plan.from_cache = False
    try:
        store_plan(plan, cache_path)
    except OSError:
        pass
    return plan


# --------------------------------------------------------------------- #
# drift guard
# --------------------------------------------------------------------- #


class PlanCell:
    """Mutable holder for a resolved plan plus its drift guard.

    The multi-node optimizer's planned reducer reads ``cell.plan`` at
    trace time; ``StandardUpdater`` feeds observed exchange wall times
    into :meth:`observe` (its ``main/exchange_time`` row).  When the
    observation departs from the plan's measured probe time by more
    than ``drift_factor``× in either direction, :attr:`drifted` flips
    — the machine changed under the plan (a congested fabric, a
    migrated VM, a different neighbour on the pod) — and the owner MAY
    call :meth:`retune`.  Re-tuning is optional and explicit: it
    recompiles every step program, so nothing here does it silently.
    """

    def __init__(self, plan: Optional[Plan] = None,
                 drift_factor: float = 2.0):
        if drift_factor <= 1.0:
            raise ValueError(
                f"drift_factor {drift_factor} must be > 1")
        self.plan = plan
        self.drift_factor = drift_factor
        self.observed_s: Optional[float] = None
        # bumped on every resolve(): consumers that baked the previous
        # plan into compiled programs (StandardUpdater's step cache)
        # compare generations and invalidate automatically — a retune
        # must never leave training silently running the old exchange
        self.generation = 0
        # constraints the original resolution was tuned under (e.g. the
        # optimizer's allow_hierarchical/inter_axis_name — what the
        # consuming step program can actually execute); retune()
        # re-applies them so a drift re-tune can never adopt a plan the
        # program cannot run
        self.tune_kwargs: Dict[str, Any] = {}
        # the search retune() re-runs: autotune_plan (the default,
        # looked up at call time) for the optimizer exchange,
        # autotune_pattern_plan for IR-lowered patterns (set by
        # whoever resolves the cell, alongside tune_kwargs)
        self.tuner: Optional[Callable[..., Plan]] = None

    def resolve(self, plan: Plan) -> None:
        self.plan = Plan.from_any(plan)
        self.observed_s = None
        self.generation += 1

    def observe(self, seconds: float) -> None:
        """Record one observed window-end exchange wall time."""
        self.observed_s = float(seconds)

    @property
    def drifted(self) -> bool:
        """This rank's LOCAL drift verdict.  Fine for observability;
        do NOT gate a collective (``retune``) on it directly in
        multi-process runs — use :meth:`should_retune`."""
        if (self.plan is None or self.observed_s is None
                or not self.plan.measured_ms):
            return False
        planned_s = self.plan.measured_ms / 1e3
        f = self.drift_factor
        return (self.observed_s > planned_s * f
                or self.observed_s < planned_s / f)

    def should_retune(self, comm=None) -> bool:
        """Rank-AGREED drift verdict: rank 0's ``drifted`` is broadcast
        so every process enters (or skips) the collective
        :meth:`retune` together.  Gating on the per-rank ``drifted``
        would deadlock a multi-host job whose hosts disagree — the
        re-tune's probe programs and winner broadcast are collectives
        some ranks would never enter.  With no ``comm`` (or a
        single-process one) this is just ``drifted``."""
        if comm is None:
            return self.drifted
        return bool(comm.bcast_obj(self.drifted, root=0))

    def retune(self, comm, params, **kwargs) -> Plan:
        """Re-run the measured search (``force=True``) under the SAME
        constraints the cell was originally resolved with
        (``tune_kwargs``, overridable per call) and adopt the winner.
        The caller owns recompilation of anything that baked the old
        plan in (``StandardUpdater._step_cache``)."""
        merged = {**self.tune_kwargs, **kwargs}
        tuner = self.tuner if self.tuner is not None else autotune_plan
        plan = tuner(comm, params, force=True, **merged)
        self.resolve(plan)
        return plan
