"""Perf regression sentinel — noise-aware verdicts over bench history.

``BENCH_MEASURED.json`` accumulates every successful bench record, but
until now nothing READ that history: a perf regression was discovered
by a human eyeballing two JSON lines, or not at all.  This module is
the first piece of perf CI — the missing start of the bench
trajectory: given a fresh bench record and the history of prior runs
of the same metric (and the same workload — batch size, sequence
length; a toy debug run must never anchor the bound), it computes a
**noise-aware acceptance bound** and emits a machine-readable verdict.

The bound is deliberately simple and robust (the history is short —
a handful of runs per metric — so anything distributional would be
noise fit to noise):

- baseline = **median** of the matching history values (robust to the
  one outlier a bursty host records);
- sigma = the scaled median absolute deviation (``1.4826 × MAD``, the
  robust stdev estimator; 0 for n < 2);
- the allowed slack is ``max(rel_slack × |median|, noise_k × sigma)``
  — a floor of ``rel_slack`` (default 5%) so a perfectly repeatable
  history doesn't flag measurement jitter, widened by the history's
  OWN observed noise when it is the larger term.

For a higher-is-better metric (throughput, speedup ratios — the
default), ``value < median − slack`` is a ``"regression"``,
``value > median + slack`` is ``"improved"``, anything between is
``"pass"``; ``direction="lower"`` mirrors the bounds for
cost metrics.  Fewer than ``min_history`` matching runs is
``"no_history"`` — evidence, not a verdict (green for gating: a new
bench's first run cannot fail against nothing).

``bench.py --check`` (and any script passing ``check=True`` through
``_bench_common.run_child_with_retries``) self-verifies: the fresh
record is scored against history BEFORE it is appended (a run must
not anchor its own bound), the verdict rides the printed JSON line
under ``"check"``, and the process exits 1 on ``"regression"`` so a
CI step can gate on it.

Pure stdlib, importable without jax.
"""

from __future__ import annotations

import json
import statistics
from typing import Dict, List, Optional, Sequence

__all__ = [
    "check_record",
    "check_value",
    "history_values",
    "load_history",
    "noise_bounds",
]

#: MAD → stdev scale for normally-distributed noise.
MAD_SCALE = 1.4826

#: Defaults: 5% relative slack floor, 3-sigma noise widening, and at
#: least 2 matching prior runs before a verdict is more than evidence.
REL_SLACK = 0.05
NOISE_K = 3.0
MIN_HISTORY = 2

#: Timestamped history entries older than this never anchor a bound —
#: the same cutoff the measurement cache's fallback applies
#: (``_bench_common.MAX_CACHE_AGE_DAYS``): a verdict against a
#: baseline measured on weeks-old code is not a verdict about this
#: tree.  Legacy un-timestamped entries pass (the leniency that
#: retires itself).
MAX_HISTORY_AGE_DAYS = 14.0


def load_history(path: str) -> List[dict]:
    """The run list from a ``BENCH_MEASURED.json``-shaped file
    (``{"runs": [...]}``); an unreadable/absent file is an empty
    history, never a crash — the sentinel must degrade to
    ``no_history``, not kill a bench."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    runs = doc.get("runs", []) if isinstance(doc, dict) else doc
    return [r for r in runs if isinstance(r, dict)]


def history_values(runs: Sequence[dict], metric: str,
                   match: Optional[dict] = None,
                   max_age_days: Optional[float] =
                   MAX_HISTORY_AGE_DAYS) -> List[float]:
    """Values of prior runs of ``metric`` whose recorded workload
    fields agree with ``match`` (the ``freshest_cached`` convention:
    a run that predates the recording of a matched field passes —
    the leniency covers legacy entries and retires itself).  Runs
    served FROM the cache (``"cached": true``) are replays of an
    earlier entry, not independent evidence, and are skipped — as are
    runs the sentinel itself scored ``regression``
    (``"check_verdict": "regression"``): a sustained real regression
    re-run by CI must not pull the baseline down until the gate
    self-normalizes green (an INTENTIONAL perf change re-anchors by
    recording a run without ``--check``, or by editing the
    history).  Timestamped runs older than ``max_age_days`` are
    skipped too (``None`` disables the cutoff)."""
    import datetime

    now = datetime.datetime.now(datetime.timezone.utc)
    out = []
    for run in runs:
        if run.get("metric") != metric or run.get("value") is None:
            continue
        if run.get("cached"):
            continue
        if run.get("check_verdict") == "regression":
            continue
        if match and any(k in run and run[k] != v
                         for k, v in match.items()):
            continue
        ts = run.get("timestamp")
        if ts is not None and max_age_days is not None:
            try:
                age = now - datetime.datetime.fromisoformat(ts)
            except (TypeError, ValueError):
                age = None
            if age is not None \
                    and age.total_seconds() > max_age_days * 86400:
                continue
        try:
            out.append(float(run["value"]))
        except (TypeError, ValueError):
            continue
    return out


def noise_bounds(values: Sequence[float],
                 rel_slack: float = REL_SLACK,
                 noise_k: float = NOISE_K) -> dict:
    """``{median, sigma, slack, lower, upper}`` over a non-empty
    history (see module docstring for the bound construction)."""
    vals = [float(v) for v in values]
    if not vals:
        raise ValueError("noise_bounds over an empty history")
    med = statistics.median(vals)
    if len(vals) >= 2:
        mad = statistics.median(abs(v - med) for v in vals)
        sigma = MAD_SCALE * mad
    else:
        sigma = 0.0
    slack = max(rel_slack * abs(med), noise_k * sigma)
    return {"median": med, "sigma": sigma, "slack": slack,
            "lower": med - slack, "upper": med + slack}


def check_value(value: float, values: Sequence[float], *,
                direction: str = "higher",
                rel_slack: float = REL_SLACK,
                noise_k: float = NOISE_K,
                min_history: int = MIN_HISTORY) -> dict:
    """Score one fresh ``value`` against its history; returns the
    machine-readable verdict block (see module docstring)."""
    if direction not in ("higher", "lower"):
        raise ValueError(
            f"direction={direction!r} must be 'higher' or 'lower'")
    n = len(values)
    if n < min_history:
        return {"verdict": "no_history", "n_history": n,
                "min_history": min_history, "direction": direction}
    b = noise_bounds(values, rel_slack=rel_slack, noise_k=noise_k)
    value = float(value)
    if direction == "higher":
        verdict = ("regression" if value < b["lower"]
                   else "improved" if value > b["upper"] else "pass")
    else:
        verdict = ("regression" if value > b["upper"]
                   else "improved" if value < b["lower"] else "pass")
    margin = ((value - b["median"]) / abs(b["median"]) * 100.0
              if b["median"] else None)
    return {
        "verdict": verdict,
        "direction": direction,
        "n_history": n,
        "baseline_median": b["median"],
        "baseline_sigma": b["sigma"],
        "slack": b["slack"],
        "lower_bound": b["lower"],
        "upper_bound": b["upper"],
        "margin_pct": None if margin is None else round(margin, 2),
    }


def check_record(record: dict, history: Sequence[dict], *,
                 match: Optional[dict] = None,
                 direction: str = "higher",
                 rel_slack: float = REL_SLACK,
                 noise_k: float = NOISE_K,
                 min_history: int = MIN_HISTORY,
                 max_age_days: Optional[float] =
                 MAX_HISTORY_AGE_DAYS) -> dict:
    """Score one bench record dict against a run history (the
    ``load_history`` shape).  A record with ``value: null`` scores
    ``"no_result"`` — the bench itself failed; the sentinel reports
    it rather than comparing nothing."""
    metric = record.get("metric")
    if record.get("value") is None:
        return {"verdict": "no_result", "metric": metric,
                "direction": direction}
    values = history_values(history, metric, match=match,
                            max_age_days=max_age_days)
    out = check_value(record["value"], values, direction=direction,
                      rel_slack=rel_slack, noise_k=noise_k,
                      min_history=min_history)
    out["metric"] = metric
    out["value"] = float(record["value"])
    if match:
        out["match"] = dict(match)
    return out
